# Developer entry points (reference Makefile analog).

.PHONY: test bench bench-small bench-smoke obs-smoke preempt-smoke \
	chaos-smoke gate-smoke gate-device-smoke pack-smoke cvx-smoke \
	aot-smoke slo-smoke topology-smoke shard-smoke policy-smoke \
	failover-smoke trace-smoke async-smoke ledger-smoke \
	smoke lint run-scheduler run-admission dryrun clean image \
	sched_image adm_image webtest_image

# container images (reference Makefile:409-435 image targets)
REGISTRY ?= yunikorn-tpu
VERSION ?= latest
DOCKER ?= docker

DOCKER_BUILD_ARGS ?=

sched_image:  ## build the scheduler image
	$(DOCKER) build $(DOCKER_BUILD_ARGS) -t $(REGISTRY)/scheduler:$(VERSION) \
		-f docker/scheduler/Dockerfile .

adm_image:  ## build the admission-controller image
	$(DOCKER) build $(DOCKER_BUILD_ARGS) -t $(REGISTRY)/admission:$(VERSION) \
		-f docker/admission/Dockerfile .

webtest_image:  ## build the webtest image
	$(DOCKER) build $(DOCKER_BUILD_ARGS) -t $(REGISTRY)/webtest:$(VERSION) \
		-f docker/webtest/Dockerfile .

image: sched_image adm_image webtest_image  ## build all three images

test:
	python -m pytest tests/ -q

test-deadlock:  ## unit tests with deadlock detection enabled (reference: make test)
	DEADLOCK_DETECTION_ENABLED=true DEADLOCK_TIMEOUT_SECONDS=30 \
		python -m pytest tests/ -q

bench:  ## end-to-end throughput on the north-star config (real TPU)
	python bench.py

bench-small:  ## CPU-friendly smoke of the bench harness
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu YK_BENCH_NODES=500 YK_BENCH_PODS=2000 \
		python bench.py

bench-smoke:  ## fast pipelined-cycle benchmark (tier-1; asserts the overlap engages + prints stage timings)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu YK_SMOKE_NODES=256 YK_SMOKE_PODS=2000 \
		python -m pytest tests/test_pipeline.py::test_pipeline_overlap_smoke -q -s

obs-smoke:  ## boot scheduler vs the synthetic client, scrape /metrics, validate the exposition + trace export (fails on unregistered-metric emission)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/obs_smoke.py

preempt-smoke:  ## batched preemption planner: differential suite (device plan == host oracle) + microbench asserting the device planner beats the host above the node-count threshold on CPU
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_preempt_solve.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/preempt_bench.py --sizes 512,4096 --assert-speedup 4096

chaos-smoke:  ## fault-injection suite: every supervised device path (assign/preempt/mesh/upload) faulted — degradation-tier result-equivalence vs fault-free schedule_once, circuit re-close after recovery, /ws/v1/health transitions, pipeline no-wedge
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_solver_chaos.py \
		tests/test_pipeline.py::test_pipeline_solve_failure_does_not_wedge \
		-q -p no:cacheprovider

gate-smoke:  ## array-form admission gate: differential suite (vector == legacy on randomized quota/limit/gang/pipelined traces) + microbench asserting the vectorized gate beats the legacy loop at >=20k asks on CPU + the churn-encode O(changed) contract
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_gate_vectorized.py \
		"tests/test_incremental_encoder.py::test_pod_batch_partial_reencode_is_o_changed" \
		-q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/gate_bench.py --sizes 2000,20000 \
		--assert-speedup 20000 --churn-check

gate-device-smoke:  ## device-resident gate+encode: differential suite (device scan == host vector == legacy, incl. pipelined/gang e2e + degradation-ladder chaos) + pass-bound regression (saturated shape <= ceil(log2 n)+C passes, never data-dependent blowup) + the O(changed) row-store upload contract
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_gate_device.py \
		tests/test_solver_chaos.py -k "gate or encode_row" \
		-q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/gate_bench.py --sizes 2000,20000 --saturated \
		--passes --device-churn-check

pack-smoke:  ## optimal packing (solver.policy=optimal): feasibility-parity property suite (pack placements pass greedy-side feasibility on randomized fragmented/priority-skew/gang/quota traces, seeded determinism, fallback on loss) + microbench asserting the pack plan beats greedy packed units on the fragmented shape with warm plan latency within 2x greedy
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_pack_solve.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/pack_bench.py --shapes 1024x128,2048x256 \
		--assert-quality

cvx-smoke:  ## CvxCluster solver arm (solver.pack=cvx): safety suite (rounding feasibility == greedy feasibility on randomized traces, strict-win-only duel commits, garbage learned dual degrades to a loss, sharded-mesh parity, fused learned-pass bit-identity) + microbench asserting the full-fleet convex plan wins the N-way duel on the fragmented shape with warm solve latency within 3x the pack solve
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_cvx_solve.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/cvx_bench.py --shapes 1024x128,2048x256 \
		--assert-quality

aot-smoke:  ## AOT cold-start elimination: store/fingerprint unit suite, then build a store offline, restart a FRESH process and assert its first cycle hits the store (aot hits > 0, zero solver compiles), is placement-identical to a cold-compiled baseline, and lands within 3x the steady-state warm cycle at the 10k-pod bucket on CPU
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_aot_store.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/aot_smoke.py

slo-smoke:  ## SLO engine + trace replay: unit suite, then a short seeded gang-storm replay through the full shim path over the fake API server — the fault-free run must show zero SLO violations, and a scripted robustness/faults.py hang on the assign path must be DETECTED as a violation (nonzero exit naming the objective)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_slo.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --fault hang \
		--slo-staleness 4 --expect-violation

topology-smoke:  ## topology-aware placement: model/steering/pack-partitioner suite (incl. the sharded-pack parity and topology-off identity contracts) + the fragmented-ICI A/B asserting >=90% of gangs land in one ICI domain within a 2x warm-latency bound
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_topology.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/topology_bench.py --shapes 384x512x16 \
		--assert-quality

shard-smoke:  ## control-plane sharding (solver.shards): ledger/partitioner/repair/parity suite (incl. the shard_parity differential oracle and the epoch re-seed storm) + a 4-shard gang-storm replay under --assert-slo with the shards fingerprint block + the shard A/B (N-shard placed/packed >= 0.97x single-shard, >= 1.5x cycle throughput, zero ledger violations)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_shard.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 --assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/shard_bench.py --shape 2000x1000x64 --shards 1,4 \
		--assert-quality

policy-smoke:  ## learned dispatch policy (solver.policy=learned): unit suite (untrained-is-inert, checkpoint REJECT-on-mismatch, N-way priority-guarded duel, ladder chaos), the 4k-node fragmented train-then-solve gate (trained checkpoint wins >= 5% packed units vs greedy with ZERO placement loss; garbage checkpoint commits bit-identical-to-greedy), and the replay round trip (record duels --dataset-out -> train -> three-arm --ab where the learned arm never loses placements)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_policy.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/policy_bench.py --train --pods 512 --nodes 4096 \
		--assert-quality
	rm -rf /tmp/yk_policy_smoke_ds
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace slice-fragmentation \
		--nodes 64 --pods 48 --tenants 2 --duration 8 --no-prewarm \
		--policy optimal --dataset-out /tmp/yk_policy_smoke_ds \
		--assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/policy_train.py --dataset /tmp/yk_policy_smoke_ds \
		--out /tmp/yk_policy_smoke_ck --imitation-epochs 30 \
		--finetune-epochs 20
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace slice-fragmentation \
		--nodes 64 --pods 48 --tenants 2 --duration 8 --no-prewarm \
		--ab --policy-checkpoint /tmp/yk_policy_smoke_ck \
		--assert-quality

failover-smoke:  ## shard failure domains + true fresh-process restart: the chaos suite (crash/wedge detection, quarantine re-homes 100% of the dead shard's domains under a clean ledger audit, fresh-core rejoin at the next epoch, watchdog-thread hygiene, cross-shard app-COUNT exactness, mis-eviction ledger across restart), a 4-shard kill-one-mid-gang-storm replay (--assert-failover: quarantined + fully re-homed + every pod bound + zero SLO violations), and a restart-storm whose mid-storm restart is a GENUINELY FRESH interpreter serving from a prebuilt AOT store within the aot_cold_start budget with zero lost bound pods and zero mis-evictions
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_failover.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 --kill-shard 1 \
		--failover-stale 30 --failover-probe 0.3 --assert-failover \
		--assert-slo
	rm -rf /tmp/yk_failover_store
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace restart-storm --nodes 300 \
		--pods 240 --tenants 4 --duration 14 --restart-mode process \
		--takeover-window 25 --aot-store /tmp/yk_failover_store \
		--slo-cold-budget-ms 120000 --assert-slo

trace-smoke:  ## fleet flight recorder (round 20): fleet-trace/journey/recorder unit suites, then the end-to-end acceptance — a 4-shard gang-storm with shard 1 killed mid-storm must export ONE merged Chrome trace (>= 5 pids, Perfetto-valid), a journey for every bound pod whose stage sum tiles its e2e latency within 5%, and exactly one quarantine bundle holding the dead shard's final cycle spans; then a hang-fault run must fire exactly one slo_violation bundle that round-trips
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_fleettrace.py tests/test_flightrec.py \
		-q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_smoke.py

async-smoke:  ## async shard front end (round 20): delivery-queue/mirror/bind-pool unit suite, a 4-shard gang-storm with shard 1 WEDGED pre-detection under --assert-slo (front-end calls must stay bounded while the failover supervisor closes in), and the shard A/B's wedged SLO pass (front call + survivor enqueue->ack p99 <= 100ms)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_async_front.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 --kill-shard 1 \
		--kill-mode wedge --failover-stale 30 --failover-probe 0.3 \
		--slo-staleness 45 --assert-failover --assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/shard_bench.py --shape 2000x1000x64 --shards 1,4 \
		--wedge-shard 1 --assert-quality --stall 6 \
		--min-speedup 0.5 --min-drain 0.3

ledger-smoke:  ## ledger-as-a-service (round 22): protocol/idempotency/degraded-mode/lease unit suite (incl. slow chaos shapes), then the chaos drills — a 4-shard gang-storm with the quota authority behind the socket and a mid-storm NETSPLIT under --assert-slo (degraded-mode admission carries the storm, journal replay reconverges, zero violations), a host-kill drill (--kill-mode lease: a stale peer lease on the liveness authority expires and its shard is quarantined/re-homed under --assert-failover), and the fail-closed starvation shape under --expect-violation (admission REJECTS while partitioned; the SLO engine must detect it)
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python -m pytest tests/test_ledger_service.py -q -p no:cacheprovider
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 \
		--ledger-socket --quota-max-vcore 10000000 --fault netsplit \
		--assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 \
		--ledger-socket --quota-max-vcore 10000000 --kill-shard 1 \
		--kill-mode lease --lease-ttl 4 --assert-failover --assert-slo
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python scripts/trace_replay.py --trace gang-storm --nodes 400 \
		--pods 320 --tenants 4 --duration 12 --shards 4 \
		--ledger-socket --quota-max-vcore 10000000 --fault netsplit \
		--ledger-fail-closed --slo-e2e 15 --expect-violation

smoke: bench-smoke obs-smoke preempt-smoke chaos-smoke gate-smoke gate-device-smoke pack-smoke cvx-smoke aot-smoke slo-smoke topology-smoke shard-smoke policy-smoke failover-smoke trace-smoke async-smoke ledger-smoke  ## all tier-1 smoke targets

run-scheduler:  ## scheduler binary with synthetic nodes + REST on :9080
	python -m yunikorn_tpu.cmd.scheduler --nodes 100

run-admission:  ## admission webhook with TLS on :9089
	python -m yunikorn_tpu.cmd.admission_controller

dryrun:  ## multi-chip sharding check on a virtual 8-device CPU mesh
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python -c "import jax; jax.config.update('jax_platforms','cpu'); \
		import __graft_entry__ as g; fn, a = g.entry(); fn(*a); g.dryrun_multichip(8)"

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
