#!/usr/bin/env python
"""Hardware A/B: Pallas fused best-node kernel vs the XLA path, on a real TPU.

Runs in phases, printing one JSON line per result as it lands (the relay can
die mid-run; earlier phases' evidence survives):

  phase 1 — kernel validation: pallas_best_nodes vs the XLA chunked path on
            random problems at several shapes, on-device (not interpret).
  phase 2 — solve-level A/B at a mid bucket (8k pods x 2k nodes), plain and
            locality-bearing batches: compile time + warm median for both
            paths; asserts identical assignments.
  phase 3 — solve-level A/B at the north-star bucket (50k x 10k), plain batch.

Usage: python scripts/tpu_ab.py [--skip-big] [--aot-store DIR]
Writes docs/PALLAS_AB.json with everything it measured.

--aot-store: consume prebuilt AOT executables (scripts/aot_build.py run
against the same jax/jaxlib + TPU topology). The solve-level phases then
load their XLA-path executables from the store instead of paying the relay
compile window — the historical blocker for this A/B (docs/PERF.md r5/r12:
the 50k-bucket remote compile alone consumed the dial budget). With a warm
store the phase-2/3 "compile_s" fields measure artifact-load, and the whole
A/B fits a bounded budget. (The pallas kernel variants still compile on
device: Mosaic kernels do not ride the PJRT executable serialization path.)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = []
OUT_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "docs", "PALLAS_AB.json")


def emit(rec):
    rec = dict(rec)
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)
    try:
        with open(OUT_PATH, "w") as f:
            json.dump(RESULTS, f, indent=1)
    except OSError:
        pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-big", action="store_true")
    ap.add_argument("--aot-store", type=str,
                    default=os.environ.get("YK_AOT_STORE", ""),
                    help="AOT executable store dir (scripts/aot_build.py): "
                         "the XLA solve paths load prebuilt executables "
                         "instead of compiling through the relay window")
    args = ap.parse_args()

    t0 = time.time()
    # bounded subprocess probes before the in-process dial: a wedged relay
    # claim costs one bounded attempt, not an indefinite hang of the A/B run
    from yunikorn_tpu.utils.jaxtools import probe_backend as _probe_backend

    budget = float(os.environ.get("YK_AB_TPU_WAIT", 600))
    dial_timeout = float(os.environ.get("YK_BENCH_TPU_DIAL_TIMEOUT", 150))
    platform = None
    attempt = 0
    while time.time() - t0 < budget:
        attempt += 1
        remaining = budget - (time.time() - t0)
        platform, n_dev, cause = _probe_backend(
            max(min(dial_timeout, remaining), 10))
        if platform == "tpu":
            break
        if platform is not None:
            # a healthy non-TPU backend (the relay down, CPU up) is still a
            # failed attempt for an A/B that NEEDS the chip — keep retrying
            # the full budget instead of aborting on the first CPU probe
            cause = f"backend up but platform={platform}, want tpu"
            platform = None
        emit({"phase": "dial", "attempt": attempt, "cause": cause,
              "elapsed_s": round(time.time() - t0, 1)})
        time.sleep(5)
    if platform is None:
        emit({"phase": "abort", "reason": f"no tpu after {attempt} dials"})
        return 1

    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    platform = devs[0].platform
    emit({"phase": "init", "platform": platform, "devices": len(devs),
          "secs": round(time.time() - t0, 1)})
    if platform != "tpu":
        emit({"phase": "abort", "reason": "not a tpu backend"})
        return 1

    from yunikorn_tpu.utils.jaxtools import ensure_compilation_cache

    if args.aot_store:
        from yunikorn_tpu import aot

        rt = aot.install(args.aot_store)
        emit({"phase": "aot-store", "path": args.aot_store,
              "entries": rt.store.entry_count()})
    ensure_compilation_cache()

    # ---------------------------------------------------------------- phase 1
    from yunikorn_tpu.ops.assign import _best_nodes_chunked
    from yunikorn_tpu.ops.pallas_kernels import pallas_best_nodes

    rng = np.random.default_rng(7)
    for (N, M, G, R) in ((512, 512, 8, 6), (2048, 1024, 64, 6), (8192, 2048, 256, 6)):
        req = rng.integers(1, 50, size=(N, R)).astype(np.int32)
        gid = rng.integers(0, G, size=(N,)).astype(np.int32)
        feas = rng.random((G, M)) < 0.7
        soft = (rng.integers(-8, 8, size=(G, M)) / 4.0).astype(np.float32)
        free = rng.integers(0, 200, size=(M, R)).astype(np.int32)
        cap = np.maximum(free, 1).astype(np.int32)
        base = (rng.integers(0, 64, size=(M,)) / 8.0).astype(np.float32)
        try:
            tpb0 = time.time()
            pb, pf = pallas_best_nodes(jnp.asarray(req), jnp.asarray(gid),
                                       jnp.asarray(feas), jnp.asarray(soft),
                                       jnp.asarray(free), jnp.asarray(base),
                                       has_soft=True)
            pb.block_until_ready()
            t_compile = time.time() - tpb0
            xb, xf = _best_nodes_chunked(jnp.asarray(req), jnp.asarray(gid),
                                         jnp.asarray(feas), jnp.asarray(soft),
                                         jnp.asarray(free), jnp.asarray(cap),
                                         jnp.asarray(base), min(512, N), "binpacking")
            pb, pf, xb, xf = (np.asarray(a) for a in (pb, pf, xb, xf))
            match_f = bool((pf == xf).all())
            match_b = bool((pb[pf] == xb[pf]).all()) if pf.any() else True
            emit({"phase": "kernel-validate", "shape": [N, M, G, R],
                  "feasible_match": match_f, "best_match": match_b,
                  "compile_s": round(t_compile, 1)})
            if not (match_f and match_b):
                diff = int((pb[pf] != xb[pf]).sum()) if pf.any() else 0
                emit({"phase": "kernel-validate-detail", "shape": [N, M, G, R],
                      "mismatches": diff})
        except Exception as e:
            emit({"phase": "kernel-validate", "shape": [N, M, G, R],
                  "error": f"{type(e).__name__}: {e}"[:500]})
            # kernel broken on hardware: no point timing the solve paths
            emit({"phase": "abort", "reason": "kernel failed on device"})
            return 2

    # ------------------------------------------------------- batch builders
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.objects import TopologySpreadConstraint
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    def build_env(n_nodes, n_pods, with_loc):
        cache = SchedulerCache()
        for i, node in enumerate(make_kwok_nodes(n_nodes)):
            node.metadata.labels["zone"] = f"z{i % 4}"
            cache.update_node(node)
        enc = SnapshotEncoder(cache)
        enc.sync_nodes(full=True)
        pods = make_sleep_pods(n_pods, "ab", queue="root.ab")
        if with_loc:
            for p in pods[: n_pods // 8]:
                p.metadata.labels["grp"] = "spread"
                p.spec.topology_spread_constraints = [TopologySpreadConstraint(
                    max_skew=1, topology_key="zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"grp": "spread"}})]
        asks = [AllocationAsk(p.uid, "ab", get_pod_resource(p), pod=p)
                for p in pods]
        return enc, enc.build_batch(asks)

    def time_solve(enc, batch, use_pallas, reps=3):
        tc0 = time.time()
        r = solve_batch(batch, enc.nodes, use_pallas=use_pallas)
        r.block_until_ready()
        compile_s = time.time() - tc0
        times = []
        for _ in range(reps):
            t1 = time.time()
            r = solve_batch(batch, enc.nodes, use_pallas=use_pallas)
            r.block_until_ready()
            times.append(time.time() - t1)
        return r, compile_s, sorted(times)[len(times) // 2]

    # ---------------------------------------------------------------- phase 2
    for with_loc in (False, True):
        enc, batch = build_env(2048, 8192, with_loc)
        try:
            rx, cx, wx = time_solve(enc, batch, use_pallas=False)
            rp, cp, wp = time_solve(enc, batch, use_pallas=True)
            ax = np.asarray(rx.assigned)[: batch.num_pods]
            ap = np.asarray(rp.assigned)[: batch.num_pods]
            emit({"phase": "solve-ab-8kx2k", "locality": with_loc,
                  "xla": {"compile_s": round(cx, 1), "warm_s": round(wx, 4)},
                  "pallas": {"compile_s": round(cp, 1), "warm_s": round(wp, 4)},
                  "identical": bool((ax == ap).all()),
                  "assigned_xla": int((ax >= 0).sum()),
                  "assigned_pallas": int((ap >= 0).sum())})
        except Exception as e:
            emit({"phase": "solve-ab-8kx2k", "locality": with_loc,
                  "error": f"{type(e).__name__}: {e}"[:500]})

    # ---------------------------------------------------------------- phase 3
    if not args.skip_big:
        enc, batch = build_env(10_000, 50_000, False)
        for name, up in (("xla", False), ("pallas", True)):
            try:
                r, cs, ws = time_solve(enc, batch, use_pallas=up, reps=3)
                emit({"phase": "solve-ab-50kx10k", "path": name,
                      "compile_s": round(cs, 1), "warm_s": round(ws, 4),
                      "assigned": int((np.asarray(r.assigned)[: batch.num_pods] >= 0).sum())})
            except Exception as e:
                emit({"phase": "solve-ab-50kx10k", "path": name,
                      "error": f"{type(e).__name__}: {e}"[:500]})

    done = {"phase": "done", "total_secs": round(time.time() - t0, 1)}
    if args.aot_store:
        from yunikorn_tpu import aot

        rt = aot.get_runtime()
        if rt is not None:
            done["aot"] = rt.stats()
    emit(done)
    return 0


if __name__ == "__main__":
    sys.exit(main())
