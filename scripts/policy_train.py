#!/usr/bin/env python
"""Offline trainer for the learned dispatch policy (solver.policy=learned).

Consumes a duel dataset recorded by `scripts/trace_replay.py --dataset-out`
or `scripts/policy_bench.py` (the CoreScheduler.policy_recorder format: raw
per-cycle solve tensors + every candidate plan + the choose_plan winner),
runs the DOPPLER-style two-phase fit (imitation of recorded duel winners,
then fine-tuning on the packed-units + contention relaxation — see
yunikorn_tpu/policy/train.py), and emits a versioned checkpoint
(`<out>.npz` + `<out>.json`) loadable via conf `solver.policyCheckpoint`.

Deterministic: same dataset + seed + hyperparameters => byte-identical
params (and therefore the same checkpoint content hash).

Usage:
    python scripts/policy_train.py --dataset /tmp/yk_policy_ds \
        --out /tmp/yk_policy_ck
    python -m yunikorn_tpu.cmd.scheduler --policy learned \
        --policy-checkpoint /tmp/yk_policy_ck
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dataset", required=True,
                    help="dataset dir (trace_replay --dataset-out)")
    ap.add_argument("--out", required=True,
                    help="checkpoint prefix (writes <out>.npz + <out>.json)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--imitation-epochs", type=int, default=80)
    ap.add_argument("--finetune-epochs", type=int, default=60)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--epoch-tag", type=int, default=None,
                    help="epoch number stamped into the manifest (defaults "
                         "to imitation+finetune epochs)")
    args = ap.parse_args()

    from yunikorn_tpu.policy import net as pnet
    from yunikorn_tpu.policy import train as ptrain

    examples = ptrain.load_dataset(args.dataset)
    if not examples:
        print(f"FAIL: no cycle examples under {args.dataset}",
              file=sys.stderr)
        return 1
    winners = {}
    for ex in examples:
        winners[ex["winner"]] = winners.get(ex["winner"], 0) + 1
    print(f"[policy-train] {len(examples)} cycles "
          f"(duel winners: {winners})", file=sys.stderr, flush=True)
    params, report = ptrain.fit(
        examples, seed=args.seed,
        imitation_epochs=args.imitation_epochs,
        finetune_epochs=args.finetune_epochs, lr=args.lr)
    epoch = (args.epoch_tag if args.epoch_tag is not None
             else args.imitation_epochs + args.finetune_epochs)
    ck = pnet.save_checkpoint(
        args.out, params, epoch=epoch,
        meta={"dataset": os.path.abspath(args.dataset),
              "cycles": len(examples), "winners": winners,
              "seed": args.seed, "report": report})
    print(json.dumps({"checkpoint": args.out, "hash": ck.hash,
                      "epoch": ck.epoch, "cycles": len(examples),
                      "winners": winners, "losses": report}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
