#!/usr/bin/env python
"""Topology-steering microbench: gang contiguity A/B on a fragmented ICI
fleet (round 15, solver.topology).

Builds the shape the topology-aware score exists for — a fleet of ICI
domains whose free capacity is pre-fragmented by co-tenant load, under a
wave of mixed-size gangs plus single-pod fillers — and A/Bs the batched
solve with and without the topology fold (topology/score.build_topo_args):

  one_domain_ratio   fraction of gangs whose every member landed inside a
                     single ICI domain (the metric the ≥0.9 acceptance
                     criterion gates)
  warm latency       steered solve wall (INCLUDING the host-side topology
                     fold) vs the un-steered solve — the ≤2x bound

Per shape prints one JSON line; --assert-quality gates the LAST shape.

--shapes 384x512x16,...   podsXnodesXdomains (default two shapes)
--assert-quality          exit 1 unless one_domain_ratio(on) >= --min-ratio,
                          it beats the off baseline, and the warm latency
                          ratio stays within --max-latency-ratio
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(n_pods: int, n_nodes: int, n_domains: int, seed: int = 0):
    """Fragmented topology fleet + a mixed gang/filler ask wave."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder
    from yunikorn_tpu.topology.model import (LABEL_ICI_DOMAIN, LABEL_RACK,
                                             LABEL_SLICE)

    rng = random.Random(seed)
    cache = SchedulerCache()
    per = max(n_nodes // n_domains, 1)
    for i in range(n_nodes):
        dom = i // per
        cache.update_node(make_node(
            f"n{i:05d}", cpu_milli=8000, memory=8 * 2**30,
            labels={LABEL_SLICE: f"slice-{dom // 8}",
                    LABEL_RACK: f"rack-{dom // 4}",
                    LABEL_ICI_DOMAIN: f"ici-{dom % 8}"}))
    # pre-fragment: co-tenant pods scattered over ~60% of the nodes, with a
    # load that leaves room for ~1 gang member — free capacity everywhere,
    # a whole gang's worth of contiguous capacity only in some domains
    busy = 0
    for i in range(n_nodes):
        if rng.random() < 0.6:
            cache.update_pod(make_pod(
                f"cot{i}", cpu_milli=rng.choice([4000, 6000]),
                memory=2**30, node_name=f"n{i:05d}"))
            busy += 1
    gangs = []
    pods = []
    g = 0
    while len(pods) < n_pods:
        size = rng.choice([2, 3, 4, 6, 8]) if rng.random() < 0.7 else 1
        size = min(size, n_pods - len(pods))
        app = f"gang-{g}" if size >= 2 else f"solo-{g}"
        members = [make_pod(f"p{g}-{j}", cpu_milli=1900, memory=2**28)
                   for j in range(size)]
        pods.extend((p, app) for p in members)
        if size >= 2:
            gangs.append((app, size))
        g += 1
    asks = [AllocationAsk(p.uid, app, get_pod_resource(p), pod=p)
            for p, app in pods]
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return enc, asks, gangs, busy


def run_shape(n_pods: int, n_nodes: int, n_domains: int) -> dict:
    import numpy as np

    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.topology.model import fleet_fragmentation
    from yunikorn_tpu.topology.score import build_topo_args

    enc, asks, gangs, busy = build(n_pods, n_nodes, n_domains)
    na = enc.nodes
    batch = enc.build_batch(asks)
    frag = fleet_fragmentation(na)

    app_of_row = {i: a.application_id for i, a in enumerate(asks)}

    def one_domain_ratio(assigned) -> float:
        doms_of = {}
        for i, node_row in enumerate(assigned.tolist()):
            app = app_of_row[i]
            if node_row >= 0:
                doms_of.setdefault(app, set()).add(int(na.topo[node_row, 2]))
            else:
                doms_of.setdefault(app, set()).add(-2)  # unplaced = split
        whole = sum(1 for app, _n in gangs
                    if len(doms_of.get(app, {-2})) == 1
                    and -2 not in doms_of[app])
        return whole / max(len(gangs), 1)

    def run_off():
        batch.topo = None
        r = solve_batch(batch, na)
        return np.asarray(r.assigned)[: batch.num_pods]

    def run_on():
        # the fold is part of the steered path's cost: include it
        batch.topo = build_topo_args(asks, batch, na, app_rows={})
        r = solve_batch(batch, na)
        return np.asarray(r.assigned)[: batch.num_pods]

    a_off = run_off()                         # cold
    t0 = time.time()
    a_off = run_off()
    off_ms = (time.time() - t0) * 1000
    a_on = run_on()                           # cold
    t0 = time.time()
    a_on = run_on()
    on_ms = (time.time() - t0) * 1000

    return {
        "pods": n_pods, "nodes": n_nodes, "domains": n_domains,
        "gangs": len(gangs), "busy_nodes": busy,
        "fragmentation": frag,
        "placed_off": int((a_off >= 0).sum()),
        "placed_on": int((a_on >= 0).sum()),
        "one_domain_ratio_off": round(one_domain_ratio(a_off), 4),
        "one_domain_ratio_on": round(one_domain_ratio(a_on), 4),
        "off_warm_ms": round(off_ms, 1),
        "on_warm_ms": round(on_ms, 1),
        "latency_ratio": round(on_ms / max(off_ms, 1e-6), 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="384x512x16,768x1024x32",
                    help="podsXnodesXdomains, comma-separated")
    ap.add_argument("--assert-quality", action="store_true",
                    help="exit 1 unless the last shape's steered solve "
                         "places >= --min-ratio of gangs in one ICI domain, "
                         "beats the un-steered baseline, and stays within "
                         "the warm-latency bound")
    ap.add_argument("--min-ratio", type=float, default=0.9)
    ap.add_argument("--max-latency-ratio", type=float, default=2.0)
    args = ap.parse_args()

    last = None
    for shape in args.shapes.split(","):
        n_pods, n_nodes, n_dom = (int(x) for x in shape.strip().split("x"))
        last = run_shape(n_pods, n_nodes, n_dom)
        print(json.dumps(last), flush=True)

    if args.assert_quality and last is not None:
        ok_ratio = last["one_domain_ratio_on"] >= args.min_ratio
        ok_beats = (last["one_domain_ratio_on"]
                    >= last["one_domain_ratio_off"])
        ok_lat = last["latency_ratio"] <= args.max_latency_ratio
        ok_placed = last["placed_on"] >= last["placed_off"] * 0.98
        if not (ok_ratio and ok_beats and ok_lat and ok_placed):
            print(f"FAIL: one_domain_ratio on={last['one_domain_ratio_on']} "
                  f"off={last['one_domain_ratio_off']} "
                  f"(need >= {args.min_ratio} and >= off), latency "
                  f"{last['latency_ratio']}x (bound "
                  f"{args.max_latency_ratio}x), placed "
                  f"{last['placed_on']} vs {last['placed_off']}",
                  file=sys.stderr)
            return 1
        print(f"OK: {last['one_domain_ratio_on']:.0%} of gangs in one ICI "
              f"domain (off baseline {last['one_domain_ratio_off']:.0%}), "
              f"warm latency {last['latency_ratio']}x <= "
              f"{args.max_latency_ratio}x", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
