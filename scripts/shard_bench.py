#!/usr/bin/env python
"""Control-plane sharding A/B: placement-quality parity + cycle throughput
of the N-shard scheduler vs the single-shard one on a fragmented topology
fleet (round 16, solver.shards; core/shard.py).

The shard_parity oracle at bench scale: the SAME workload (mixed-size gangs
plus single-pod fillers over ICI-labeled nodes pre-fragmented by co-tenant
load) runs through each shard count in --shards, direct core API (no shim),
with the shards' own staggered cycle loops doing the work:

  placed / packed units   the POP-quality gate: N shards solving disjoint
                          topology-aligned partitions (plus the stranded-ask
                          repair pass) must place >= 0.97x the single-shard
                          plan — partitioning must not cost placements
  throughput              placed pods per second of measured wall, warm
                          (one discarded warm pass compiles every bucket
                          first) — the reason the control plane is sharded:
                          N concurrent cycle loops over M/N-node partitions
                          beat one loop over M nodes
  quota violations        the shared GlobalQuotaLedger's audit must be
                          empty at every shard count (exact cross-shard
                          coupling, never double-spent)

Per shard count prints one JSON line; --assert-quality gates the LAST count
against the FIRST (canonically 1): placed/packed >= --min-quality (0.97)
and throughput >= --min-speedup (1.5) with zero ledger violations.

  --shape PODSxNODESxDOMAINS   default 4000x2000x128 (smoke); the round-16
                               PERF table runs 20000x10000x640
  --shards 1,4                 shard counts, compared last-vs-first
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

QUEUES_YAML = """
partitions:
  - name: default
    queues:
      - name: root
        queues:
          - name: tenants
"""


def build_workload(n_pods: int, n_nodes: int, n_domains: int, seed: int = 0):
    """Deterministic fleet + ask wave shared by every shard count."""
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.topology.model import (LABEL_ICI_DOMAIN, LABEL_RACK,
                                             LABEL_SLICE)

    rng = random.Random(seed)
    per = max(n_nodes // n_domains, 1)
    nodes = []
    for i in range(n_nodes):
        dom = i // per
        nodes.append(make_node(
            f"n{i:05d}", cpu_milli=8000, memory=8 * 2 ** 30,
            labels={LABEL_SLICE: f"slice-{dom // 8}",
                    LABEL_RACK: f"rack-{dom // 4}",
                    LABEL_ICI_DOMAIN: f"ici-{dom % 8}"}))
    # co-tenant fragmentation: Running pods bound to ~55% of the nodes,
    # heavy enough that a gang member still fits but contiguous gang-sized
    # capacity survives only in some domains
    cotenants = []
    for i in range(n_nodes):
        if rng.random() < 0.55:
            cotenants.append(make_pod(
                f"cot-{i}", cpu_milli=rng.choice([4000, 6000]),
                memory=2 ** 30, node_name=f"n{i:05d}", phase="Running"))
    # mixed-size gangs + fillers (the slice-fragmentation trace's shape)
    asks = []
    i = 0
    app_n = 0
    while i < n_pods:
        size = rng.choice([1, 1, 2, 3, 5, 8])
        size = min(size, n_pods - i)
        app_id = f"bench-app-{app_n}"
        app_n += 1
        for j in range(size):
            pod = make_pod(f"bp-{app_n}-{j}", cpu_milli=1000,
                           memory=2 ** 30)
            asks.append((app_id, AllocationAsk(
                allocation_key=f"bp-{app_n}-{j}",
                application_id=app_id,
                resource=get_pod_resource(pod), pod=pod)))
        i += size
    return nodes, cotenants, asks


def _percentiles(samples, qs=(0.5, 0.95, 0.99)):
    """Exact percentiles of a sample list (ms), nearest-rank."""
    if not samples:
        return {f"p{int(q * 100)}": 0.0 for q in qs} | {"max": 0.0}
    xs = sorted(samples)
    out = {}
    for q in qs:
        idx = min(len(xs) - 1, max(0, int(round(q * len(xs))) - 1))
        out[f"p{int(q * 100)}"] = round(xs[idx], 3)
    out["max"] = round(xs[-1], 3)
    return out


def _hist_percentile(state, buckets, q):
    """Upper-bound percentile estimate from a histogram child_state
    snapshot (the enqueue->ack ladder): the bucket edge where the
    cumulative count crosses the quantile. +Inf overflow reports the top
    edge. Works on a SNAPSHOT so teardown traffic (quarantine re-homing
    floods the survivors) cannot pollute the measured window."""
    count, _total, counts = state
    if count == 0:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            return float(buckets[i]) if i < len(buckets) \
                else float(buckets[-1])
    return float(buckets[-1])


def run_pass(shards: int, nodes, cotenants, asks, interval: float,
             stall_s: float, timeout_s: float, wave: int = 256,
             wave_gap_s: float = 0.01, wedge_shard=None):
    """One measured pass: fresh cache+scheduler, the shards' own cycle
    loops drain the wave. Returns the result dict.

    wedge_shard (sharded counts only): after the fleet registers, that
    shard's assign dispatch is slow-faulted past every deadline — the
    cycle thread wedges INSIDE the core holding its lock, exactly the
    pre-detection stall shape. The front-end call-latency percentiles
    then measure what the async delivery queues bought: every submit
    must return in fast constant time even though one shard is dead and
    the failover supervisor (default generous budgets) has not noticed.
    """
    import threading

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationRequest,
        ApplicationRequest,
        NodeAction,
        NodeInfo,
        NodeRequest,
        RegisterResourceManagerRequest,
        ResourceManagerCallback,
        UserGroupInfo,
    )
    from yunikorn_tpu.core.shard import make_core_scheduler

    class CountingCallback(ResourceManagerCallback):
        def __init__(self):
            self.mu = threading.Lock()
            self.placed = {}
            self.last_place_at = time.time()

        def update_allocation(self, response):
            if response.new:
                with self.mu:
                    for a in response.new:
                        self.placed[a.allocation_key] = a
                    self.last_place_at = time.time()

        def update_application(self, response):
            pass

        def update_node(self, response):
            pass

        def predicates(self, args):
            return None

        def preemption_predicates(self, args):
            return []

        def send_event(self, events):
            pass

        def update_container_scheduling_state(self, request):
            pass

        def get_state_dump(self):
            return "{}"

    cache = SchedulerCache()
    cb = CountingCallback()
    core = make_core_scheduler(cache, shards=shards, interval=interval)
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="bench", policy_group="queues",
                                       config=QUEUES_YAML), cb)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE,
                              node=n))
    core.update_node(NodeRequest(nodes=infos))
    for p in cotenants:
        cache.update_pod(p)
    app_ids = sorted({a for a, _ in asks})
    core.update_application(ApplicationRequest(new=[
        AddApplicationRequest(application_id=a, queue_name="root.tenants",
                              user=UserGroupInfo(user="bench",
                                                 groups=["bench"]))
        for a in app_ids]))
    # STREAMING arrival: the wave lands in bursts, not one batch — the
    # single-shard ceiling under test is cycle RATE (every pod in the
    # fleet flows through one pipelined cycle loop), and one monolithic
    # submit would let a single giant batched solve hide it
    bursts = [asks[i:i + wave] for i in range(0, len(asks), wave)]
    if wedge_shard is not None and shards > 1:
        k = int(wedge_shard) % shards
        # wedge INSIDE the dispatch: deadline too big to trip, the cycle
        # thread blocks holding the core lock (pre-detection, the
        # supervisor's default stale budget is far past this bench)
        core.shards[k].supervisor.options.deadline_s = 3600.0
        core.shards[k].supervisor.faults.slow("assign", seconds=3600.0,
                                              times=1_000_000)
    call_ms = []
    t0 = time.time()
    core.start()
    try:
        for burst in bursts:
            t_c = time.time()
            core.update_allocation(
                AllocationRequest(asks=[a for _, a in burst]))
            call_ms.append((time.time() - t_c) * 1000.0)
            time.sleep(wave_gap_s)
        while True:
            with cb.mu:
                placed = len(cb.placed)
                last = cb.last_place_at
            if placed >= len(asks):
                break
            now = time.time()
            if now - t0 > timeout_s:
                break
            if placed and now - last > stall_s:
                break  # quiesced: whatever is left is unplaceable
            time.sleep(0.02)
        # snapshot the ack ladder BEFORE teardown: the wedge-teardown
        # quarantine re-homes the victim's asks through the survivors'
        # queues, and those (legitimately slow) teardown acks must not
        # land in the measured percentiles
        if shards > 1:
            h = core.obs.get("shard_delivery_ack_ms")
            ack_state = {k: h.child_state(shard=str(k))
                         for k in range(shards)} if h else {}
            ack_buckets = h.buckets if h else ()
        else:
            ack_state, ack_buckets = {}, ()
    finally:
        if wedge_shard is not None and shards > 1:
            # the victim is wedged but UNDETECTED, so stop() would join
            # into its held lock; quarantine first — the teardown path
            # built for wedged cores — and stop() skips the zombie
            try:
                core.quarantine_shard(int(wedge_shard) % shards,
                                      reason="bench wedge teardown")
            except Exception:
                pass
        core.stop()
    with cb.mu:
        placed_allocs = list(cb.placed.values())
    wall = (max(cb.last_place_at - t0, 1e-6) if placed_allocs
            else max(time.time() - t0, 1e-6))
    packed = sum(a.resource.get("cpu") or 0 for a in placed_allocs)
    # PRODUCTIVE cycles only: cycle_stage_ms records an entry per cycle
    # that ADMITTED pods — idle loop iterations (which trivially scale
    # with the shard count) must not inflate the throughput gate
    hist = core.obs.get("cycle_stage_ms")

    def admitted_cycles(**labels):
        try:
            return int(hist.child_state(stage="total", **labels)[0])
        except Exception:
            return 0

    if shards > 1:
        violations = core.ledger.audit()
        srep = core.shard_report()
        per_shard = [admitted_cycles(shard=str(k)) for k in range(shards)]
        cycles = sum(per_shard)
        # wedged shard excluded: its pump never acks (that IS the wedge);
        # the survivors' ack ladder shows what delivery actually costs
        live = [k for k in range(shards)
                if wedge_shard is None or k != int(wedge_shard) % shards]

        def ack_pct(q):
            return max((_hist_percentile(ack_state[k], ack_buckets, q)
                        for k in live if k in ack_state), default=0.0)

        extra = {"bound_per_shard": [s["bound"] for s in srep["shards"]],
                 "cycles_per_shard": per_shard,
                 "repair": srep["repair"],
                 "ledger": srep["ledger"],
                 "delivery": [s["delivery"] for s in srep["shards"]],
                 # enqueue->APPLY (the pump finished applying the payload,
                 # bucket upper bounds): solve-bound by design — a delivery
                 # landing mid-solve waits for the core lock. Context for
                 # the gated number, which is front_call_ms (enqueue->ack
                 # back to the caller — what the async front end bounds)
                 "delivery_apply_ms": {"p50": ack_pct(0.5),
                                       "p95": ack_pct(0.95),
                                       "p99": ack_pct(0.99)},
                 "wedged_shard": (None if wedge_shard is None
                                  else int(wedge_shard) % shards)}
    else:
        violations = []
        cycles = admitted_cycles()
        extra = {}
    return {
        "shards": shards,
        "placed": len(placed_allocs),
        "asked": len(asks),
        "packed_units": int(packed),
        "wall_s": round(wall, 3),
        "cycles": cycles,
        # the ROADMAP ceiling under test: scheduling cycles completed per
        # second of measured wall — N concurrent loops over M/N-node
        # partitions must beat the one loop every pod used to flow through
        "throughput_cycles_s": round(cycles / wall, 2),
        "throughput_pods_s": round(len(placed_allocs) / wall, 1),
        "quota_violations": len(violations),
        # the async-front measurement: wall time each front-end submit
        # call spent before returning (enqueue-and-return — bounded even
        # with a wedged shard; pre-round-20 a wedge made this unbounded)
        "front_call_ms": _percentiles(call_ms),
        **extra,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="4000x2000x128",
                    help="PODSxNODESxDOMAINS")
    ap.add_argument("--shards", default="1,4",
                    help="comma-separated shard counts; --assert-quality "
                         "compares the LAST against the FIRST")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--interval", type=float, default=0.005)
    ap.add_argument("--wave", type=int, default=256,
                    help="streaming burst size (pods per submit)")
    ap.add_argument("--wave-gap", type=float, default=0.01,
                    help="gap between bursts, seconds")
    ap.add_argument("--stall", type=float, default=3.0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--assert-quality", action="store_true",
                    help="exit 1 unless last-vs-first placed AND packed "
                         "units >= --min-quality, throughput >= "
                         "--min-speedup, and zero ledger violations")
    ap.add_argument("--min-quality", type=float, default=0.97)
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="admitted-cycle throughput floor. NOTE: per-shard "
                         "partitions admit in parallel, so this ratio "
                         "scales with the shard count by construction — "
                         "it gates that the cycle loops actually run "
                         "concurrently, not real drain rate; pair it with "
                         "--min-drain for that")
    ap.add_argument("--min-drain", type=float, default=0.5,
                    help="pods-per-second floor vs the first shard count "
                         "— the REAL throughput gate (sharding must never "
                         "cost more than this factor; >1 asserts a win, "
                         "as at the 10k streaming shape)")
    ap.add_argument("--wedge-shard", type=int, default=None,
                    help="after the normal passes, run ONE extra pass at "
                         "the last shard count with this shard wedged "
                         "inside its dispatch (pre-detection) and report "
                         "front-end call + enqueue->ack percentiles for "
                         "the survivors — the async-front-end SLO run")
    ap.add_argument("--assert-call-p99", type=float, default=100.0,
                    help="with --assert-quality and --wedge-shard: fail "
                         "unless the wedged pass's front-end call "
                         "(enqueue->ack) p99 stays at or under this many "
                         "ms — the pre-detection-stall SLO")
    args = ap.parse_args()

    n_pods, n_nodes, n_domains = (int(x) for x in args.shape.split("x"))
    counts = [int(x) for x in args.shards.split(",")]
    nodes, cotenants, asks = build_workload(n_pods, n_nodes, n_domains,
                                            seed=args.seed)
    print(f"# shard_bench: {n_pods} pods x {n_nodes} nodes x "
          f"{n_domains} domains, shard counts {counts}", file=sys.stderr,
          flush=True)
    results = []
    for shards in counts:
        # warm pass compiles this shard count's bucket shapes (per-shard
        # partitions land in smaller buckets than the full fleet); a
        # bounded prefix of the workload is enough to touch them — the
        # solve chunks pods, so the big-wave programs are the same
        warm = asks[:min(len(asks), max(args.wave * 8, 2048))]
        run_pass(shards, nodes, cotenants, warm, args.interval,
                 args.stall, args.timeout, wave=args.wave,
                 wave_gap_s=args.wave_gap)
        res = run_pass(shards, nodes, cotenants, asks, args.interval,
                       args.stall, args.timeout, wave=args.wave,
                       wave_gap_s=args.wave_gap)
        results.append(res)
        print(json.dumps(res), flush=True)
    wedged_res = None
    if args.wedge_shard is not None and counts[-1] > 1:
        # the SLO pass: same workload, last shard count, one shard wedged
        # pre-detection. Placement CANNOT complete (the victim's partition
        # is dead) — the stall window quiesces the pass; what this pass
        # measures is that every front-end call stays bounded anyway.
        wedged_res = run_pass(counts[-1], nodes, cotenants, asks,
                              args.interval, args.stall, args.timeout,
                              wave=args.wave, wave_gap_s=args.wave_gap,
                              wedge_shard=args.wedge_shard)
        wedged_res["wedged"] = True
        print(json.dumps(wedged_res), flush=True)
    if args.assert_quality:
        base, best = results[0], results[-1]
        q_placed = best["placed"] / max(base["placed"], 1)
        q_packed = best["packed_units"] / max(base["packed_units"], 1)
        speedup = (best["throughput_cycles_s"]
                   / max(base["throughput_cycles_s"], 1e-9))
        drain = (best["throughput_pods_s"]
                 / max(base["throughput_pods_s"], 1e-9))
        ok = (q_placed >= args.min_quality
              and q_packed >= args.min_quality
              and speedup >= args.min_speedup
              and drain >= args.min_drain
              and best["quota_violations"] == 0)
        print(f"# shard_bench: {best['shards']}-shard vs "
              f"{base['shards']}-shard: placed {q_placed:.3f}x, packed "
              f"{q_packed:.3f}x, cycle throughput {speedup:.2f}x, drain "
              f"{drain:.2f}x, violations {best['quota_violations']} -> "
              f"{'PASS' if ok else 'FAIL'}", file=sys.stderr, flush=True)
        if wedged_res is not None:
            call_p99 = wedged_res["front_call_ms"]["p99"]
            apply_p99 = wedged_res["delivery_apply_ms"]["p99"]
            slo_ok = (call_p99 <= args.assert_call_p99
                      and wedged_res["quota_violations"] == 0)
            print(f"# shard_bench SLO (shard {wedged_res['wedged_shard']} "
                  f"wedged pre-detection): front call (enqueue->ack) p99 "
                  f"{call_p99}ms vs budget {args.assert_call_p99}ms; "
                  f"survivor delivery-apply p99 <= {apply_p99}ms "
                  f"(solve-bound, not gated) -> "
                  f"{'PASS' if slo_ok else 'FAIL'}",
                  file=sys.stderr, flush=True)
            ok = ok and slo_ok
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
