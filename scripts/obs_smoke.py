#!/usr/bin/env python
"""obs-smoke: end-to-end observability check (`make obs-smoke`).

Boots the full scheduler (real core + real shim) against the synthetic
client, binds a pod wave plus one deliberately unschedulable ask, then:

  1. scrapes `/metrics` and validates the whole exposition with the mini
     Prometheus parser (obs/promtext): every sample must belong to a
     `# TYPE`-declared family — any unregistered-metric emission fails —
     histogram buckets must be cumulative/monotone with +Inf == _count,
     and the required families (pod e2e latency histogram, labelled
     unschedulable_total, dispatcher counters) must be present;
  2. checks `/debug/traces` serves Chrome trace-event JSON containing the
     cycle-stage spans;
  3. checks the JSON twin `/ws/v1/metrics` renders from the same registry.

Exit status is the CI contract: 0 = all green, 1 = printed failures.
"""
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _get(port: int, path: str) -> bytes:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.read()


def main() -> int:
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.obs.promtext import (parse_exposition,
                                           validate_exposition)
    from yunikorn_tpu.shim.mock_scheduler import MockScheduler
    from yunikorn_tpu.webapp.rest import RestServer

    n_nodes = int(os.environ.get("YK_OBS_SMOKE_NODES", 32))
    n_pods = int(os.environ.get("YK_OBS_SMOKE_PODS", 200))
    errors = []
    t0 = time.time()
    ms = MockScheduler()
    ms.init(interval=0.05, core_interval=0.02,
            conf_extra={"log.level": "WARN"})
    rest = None
    text, trace_names = "", set()
    try:
        for node in make_kwok_nodes(n_nodes):
            ms.cluster.add_node(node)
        pods = make_sleep_pods(n_pods, "obs-app", queue="root.obs",
                               name_prefix="obs")
        # one ask no node can ever hold: must surface as a labelled
        # unschedulable_total{reason="capacity"} count, not vanish. High
        # priority makes it preemption-ELIGIBLE too, so the batched victim
        # planner runs a (necessarily fruitless) pass and the preemption
        # plan-latency histogram gets a sample — declared-but-never-emitted
        # histograms fail validation below.
        giant = make_sleep_pods(1, "obs-app", queue="root.obs",
                                name_prefix="obs-giant", cpu_milli=10**9)
        giant[0].spec.priority = 100
        for p in pods + giant:
            ms.cluster.add_pod(p)
        ms.start()
        ms.wait_for_bound_count(n_pods, timeout=120)
        deadline = time.time() + 30
        while time.time() < deadline:
            hist = ms.core.obs.get("preemption_plan_ms")
            if hist is not None and any(
                    hist.child_state(planner=pl)[0]
                    for pl in ("device", "host")):
                break
            time.sleep(0.2)
        rest = RestServer(ms.core, ms.context, port=0)
        port = rest.start()

        text = _get(port, "/metrics").decode()
        errors += validate_exposition(text, required=(
            "yunikorn_allocation_attempt_allocated",
            "yunikorn_solve_count",
            "yunikorn_pod_e2e_latency_seconds",
            "yunikorn_pod_stage_latency_seconds",
            "yunikorn_cycle_stage_ms",
            "yunikorn_unschedulable_total",
            "yunikorn_dispatcher_events_total",
            "yunikorn_preemption_plan_ms",
            "yunikorn_slo_burn_rate",
            "yunikorn_slo_violations_total",
            "yunikorn_slo_verdict",
            "yunikorn_slo_objective_value",
            "yunikorn_journey_stage_ms",
            "yunikorn_journey_completed_total",
            "yunikorn_journey_terminal_total",
            "yunikorn_flight_recordings_total",
            "yunikorn_bind_pool_depth",
            "yunikorn_bind_pool_tasks_total",
        ))
        fams = parse_exposition(text)
        # the slo_* series must carry the declared TYPEs and labels (a
        # mistyped burn-rate gauge would silently break every dashboard
        # rate()/threshold rule built on it)
        for name, kind in (("yunikorn_slo_burn_rate", "gauge"),
                           ("yunikorn_slo_violations_total", "counter"),
                           ("yunikorn_slo_verdict", "gauge"),
                           ("yunikorn_slo_objective_value", "gauge")):
            fam = fams.get(name)
            if fam is None:
                continue  # missing already reported by `required` above
            if fam.kind != kind:
                errors.append(f"{name}: TYPE {fam.kind!r}, expected {kind!r}")
            if not all(s.labels.get("objective") for s in fam.samples):
                errors.append(f"{name}: samples missing the objective label")
        # round-20 journey/flight-recorder families: declared TYPEs (the
        # Grafana row's histogram_quantile/rate() rules depend on them)
        for name, kind in (
                ("yunikorn_journey_stage_ms", "histogram"),
                ("yunikorn_journey_completed_total", "counter"),
                ("yunikorn_journey_terminal_total", "counter"),
                ("yunikorn_flight_recordings_total", "counter")):
            fam = fams.get(name)
            if fam is None:
                continue  # missing already reported by `required` above
            if fam.kind != kind:
                errors.append(f"{name}: TYPE {fam.kind!r}, expected {kind!r}")
        jterm = fams.get("yunikorn_journey_terminal_total")
        if jterm and not all(s.labels.get("outcome") for s in jterm.samples):
            errors.append("journey_terminal_total: samples missing the "
                          "outcome label")
        frec = fams.get("yunikorn_flight_recordings_total")
        if frec and not all(s.labels.get("trigger") for s in frec.samples):
            errors.append("flight_recordings_total: samples missing the "
                          "trigger label")
        burn = fams.get("yunikorn_slo_burn_rate")
        if burn:
            windows = {s.labels.get("window") for s in burn.samples}
            if windows != {"fast", "slow"}:
                errors.append(f"slo_burn_rate windows {sorted(windows)} != "
                              "fast/slow")
        e2e = fams.get("yunikorn_pod_e2e_latency_seconds")
        bound_obs = next(
            (s.value for s in (e2e.samples if e2e else [])
             if s.name.endswith("_count")), 0)
        if bound_obs < n_pods:
            errors.append(f"pod_e2e_latency_seconds_count {bound_obs} < "
                          f"bound pods {n_pods}")
        uns = fams.get("yunikorn_unschedulable_total")
        if not uns or not any(s.labels.get("reason") for s in uns.samples):
            errors.append("unschedulable_total has no reason-labelled samples")
        # round-20 bind pool: the wave quiesced, so depth must be a STABLE
        # ZERO (queued+inflight drained) while tasks_total carries the binds
        bpd = fams.get("yunikorn_bind_pool_depth")
        if bpd and any(s.value != 0 for s in bpd.samples):
            errors.append("bind_pool_depth nonzero after quiesce: "
                          f"{[(s.labels, s.value) for s in bpd.samples]}")
        bpt = fams.get("yunikorn_bind_pool_tasks_total")
        bound_binds = sum(s.value for s in (bpt.samples if bpt else []))
        if bound_binds < n_pods:
            errors.append(f"bind_pool_tasks_total {bound_binds} < bound "
                          f"pods {n_pods}")

        trace = json.loads(_get(port, "/debug/traces"))
        trace_names = {e.get("name") for e in trace.get("traceEvents", [])}
        for need in ("encode", "solve", "commit", "preempt"):
            if need not in trace_names:
                errors.append(f"/debug/traces missing {need!r} spans "
                              f"(got {sorted(trace_names)})")

        mjson = json.loads(_get(port, "/ws/v1/metrics"))
        if mjson.get("allocation_attempt_allocated", 0) < n_pods:
            errors.append("/ws/v1/metrics allocation count below bound pods")
        if "pod_e2e_latency_seconds" not in mjson:
            errors.append("/ws/v1/metrics missing the e2e histogram family")
    finally:
        if rest is not None:
            rest.stop()
        ms.stop()

    # ---- round-20 async front end: the sharded boot's families ----------
    # the default boot is single-shard (plain CoreScheduler — no delivery
    # queues), so the queue-depth/ack/shed/mirror families need a small
    # 2-shard boot of the SAME full stack; after the wave quiesces every
    # depth gauge and the shed/divergence series must read a stable zero
    ms2 = MockScheduler()
    ms2.init(interval=0.05, core_interval=0.02,
             conf_extra={"log.level": "WARN", "solver.shards": "2"})
    rest2 = None
    try:
        for node in make_kwok_nodes(8):
            ms2.cluster.add_node(node)
        for p in make_sleep_pods(24, "obs-sharded", queue="root.obs",
                                 name_prefix="obs2"):
            ms2.cluster.add_pod(p)
        ms2.start()
        ms2.wait_for_bound_count(24, timeout=120)
        rest2 = RestServer(ms2.core, ms2.context, port=0)
        port2 = rest2.start()
        fams2 = parse_exposition(_get(port2, "/metrics").decode())
        for name in ("yunikorn_shard_queue_depth",
                     "yunikorn_shard_delivery_ack_ms",
                     "yunikorn_shard_queue_shed_total",
                     "yunikorn_shard_ledger_mirror_divergence",
                     "yunikorn_bind_pool_depth",
                     "yunikorn_bind_pool_tasks_total"):
            if name not in fams2:
                errors.append(f"sharded boot: /metrics missing {name}")
        qd = fams2.get("yunikorn_shard_queue_depth")
        if qd:
            shards_seen = {s.labels.get("shard") for s in qd.samples}
            if not {"0", "1"} <= shards_seen:
                errors.append(f"shard_queue_depth shards {shards_seen} "
                              "missing 0/1")
            if any(s.value != 0 for s in qd.samples):
                errors.append("shard_queue_depth nonzero after quiesce")
        ack = fams2.get("yunikorn_shard_delivery_ack_ms")
        if ack and not any(s.name.endswith("_count") and s.value > 0
                           for s in ack.samples):
            errors.append("shard_delivery_ack_ms never observed an ack")
        shed = fams2.get("yunikorn_shard_queue_shed_total")
        if shed and any(s.value != 0 for s in shed.samples):
            errors.append("shard_queue_shed_total nonzero under a load "
                          "far below high-water")
        div = fams2.get("yunikorn_shard_ledger_mirror_divergence")
        if div and any(s.value != 0 for s in div.samples):
            errors.append("shard_ledger_mirror_divergence nonzero: device "
                          "mirror disagrees with the ledger")
        bpd2 = fams2.get("yunikorn_bind_pool_depth")
        if bpd2:
            if {s.labels.get("shard") for s in bpd2.samples} < {"0", "1"}:
                errors.append("sharded bind_pool_depth missing per-shard "
                              "series")
            if any(s.value != 0 for s in bpd2.samples):
                errors.append("sharded bind_pool_depth nonzero after "
                              "quiesce")
    finally:
        if rest2 is not None:
            rest2.stop()
        ms2.stop()
    if errors:
        print("obs-smoke FAILED:")
        for e in errors:
            print(f" - {e}")
        return 1
    print(f"obs-smoke OK in {time.time() - t0:.1f}s: {n_pods} pods bound "
          f"over {n_nodes} nodes; exposition valid "
          f"({len(text.splitlines())} lines, {len(parse_exposition(text))} "
          f"families); trace spans: {sorted(trace_names)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
