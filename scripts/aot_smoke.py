#!/usr/bin/env python
"""AOT cold-start smoke: prove a FRESH process with a prebuilt store serves
its first scheduling cycle from stored executables.

Phases (each child is its own process — cross-process is the whole point):

  build   — scripts/aot_build.py populates a temp store at the smoke bucket.
  hit     — a fresh child replays the same trace WITH the store. Asserts:
              * aot hits > 0 and ZERO aot-path compiles (every solver
                program the cycle dispatched came from the store),
              * the core counted no solve compiles
                (solve_compile_total == 0).
  cold    — a fresh child replays the same trace WITHOUT the store
            (the legacy --prewarm-style trace+compile cold start).
  compare — placements of the hit child are IDENTICAL to the cold child's
            (a deserialized executable is the same program, bit for bit),
            and the store-hit first cycle is within --max-ratio x the
            steady-state warm cycle (default 3, the acceptance bound)
            while the cold child's first cycle shows the compile stall.

Usage:
  python scripts/aot_smoke.py [--bucket 1024x10240] [--max-ratio 3]
  python scripts/aot_smoke.py --child run --store DIR --bucket NxP  (internal)
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(SCRIPTS_DIR))
sys.path.insert(0, SCRIPTS_DIR)

# the acceptance bucket: 10k pods (the documented CPU bucket's pod count,
# docs/PERF.md) — big enough that the compile stall dominates a cold first
# cycle and the ≤3x store-hit bound is a real statement
DEFAULT_BUCKET = "1024x10240"


def _digest(placements: dict) -> str:
    blob = json.dumps(sorted(placements.items())).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def child_run(store: str, n_nodes: int, n_pods: int) -> int:
    """One fresh-process trace replay; prints a single JSON line."""
    from yunikorn_tpu.utils.jaxtools import force_cpu_platform

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        force_cpu_platform(1)
    rt = None
    if store:
        from yunikorn_tpu import aot

        rt = aot.install(store, background=False)
    from aot_build import run_trace

    t0 = time.time()
    res = run_trace(n_nodes, n_pods)
    out = {
        "placements_digest": _digest(res["placements"]),
        "placed": len(res["placements"]),
        "first_cycle_ms": round(res["first_cycle_ms"], 1),
        "steady_ms": round(res["steady_ms"], 1),
        "wall_s": round(time.time() - t0, 1),
        "aot_hits": rt.stats()["hits"] if rt else 0,
        "aot_compiles": rt.stats()["compiles"] if rt else 0,
        "aot_loads": rt.stats()["loads"] if rt else 0,
    }
    print(json.dumps(out), flush=True)
    return 0


def _spawn(store: str, bucket: str, timeout: float) -> dict:
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.abspath(__file__), "--child", "run",
           "--store", store, "--bucket", bucket]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                       env=env)
    if r.returncode != 0:
        print(r.stdout, file=sys.stderr)
        print(r.stderr, file=sys.stderr)
        raise SystemExit(f"child failed rc={r.returncode}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bucket", default=DEFAULT_BUCKET)
    ap.add_argument("--max-ratio", type=float, default=3.0,
                    help="store-hit first cycle must be within this factor "
                         "of the steady-state warm cycle")
    ap.add_argument("--store", default="",
                    help="reuse an existing store instead of building a "
                         "temp one (skips the build phase)")
    ap.add_argument("--child", default="", help="internal")
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args()

    n_nodes, n_pods = (int(x) for x in args.bucket.lower().split("x"))
    if args.child == "run":
        return child_run(args.store, n_nodes, n_pods)

    tmp = None
    store = args.store
    if not store:
        tmp = tempfile.mkdtemp(prefix="aot-smoke-")
        store = os.path.join(tmp, "store")
        t0 = time.time()
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, os.path.join(SCRIPTS_DIR, "aot_build.py"),
             "--store", store, "--buckets", args.bucket, "--no-variants"],
            capture_output=True, text=True, timeout=args.timeout, env=env)
        if r.returncode != 0:
            print(r.stdout, file=sys.stderr)
            print(r.stderr, file=sys.stderr)
            raise SystemExit(f"aot_build failed rc={r.returncode}")
        print(f"# build: {r.stdout.strip().splitlines()[-1]} "
              f"({time.time() - t0:.1f}s)", file=sys.stderr, flush=True)

    hit = _spawn(store, args.bucket, args.timeout)
    print(f"# store-hit fresh process: {json.dumps(hit)}",
          file=sys.stderr, flush=True)
    cold = _spawn("", args.bucket, args.timeout)
    print(f"# cold-compile fresh process: {json.dumps(cold)}",
          file=sys.stderr, flush=True)

    failures = []
    if hit["aot_hits"] <= 0:
        failures.append(f"expected store hits, got {hit['aot_hits']}")
    if hit["aot_compiles"] != 0:
        failures.append(
            f"store-hit run compiled {hit['aot_compiles']} solver programs "
            "(store coverage gap)")
    if hit["placements_digest"] != cold["placements_digest"]:
        failures.append(
            f"placement drift: store-hit {hit['placements_digest']} != "
            f"cold {cold['placements_digest']}")
    if hit["placed"] <= 0:
        failures.append("store-hit run placed nothing")
    ratio = (hit["first_cycle_ms"] / hit["steady_ms"]
             if hit["steady_ms"] > 0 else float("inf"))
    if ratio > args.max_ratio:
        failures.append(
            f"store-hit first cycle {hit['first_cycle_ms']}ms is "
            f"{ratio:.2f}x steady {hit['steady_ms']}ms "
            f"(> {args.max_ratio}x)")

    result = {
        "bucket": args.bucket,
        "ok": not failures,
        "store_hit_first_cycle_ms": hit["first_cycle_ms"],
        "steady_ms": hit["steady_ms"],
        "first_vs_steady": round(ratio, 2),
        "cold_first_cycle_ms": cold["first_cycle_ms"],
        "cold_speedup": round(cold["first_cycle_ms"]
                              / max(hit["first_cycle_ms"], 0.1), 1),
        "aot_hits": hit["aot_hits"],
        "aot_compiles": hit["aot_compiles"],
        "placement_identical":
            hit["placements_digest"] == cold["placements_digest"],
        "failures": failures,
    }
    print(json.dumps(result))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
