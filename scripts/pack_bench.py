#!/usr/bin/env python
"""Pack-solver microbench: LP/ADMM global packing vs the greedy argmin.

Builds the fragmentation shapes where a global view pays — heterogeneous
node flavors (cpu-rich/mem-poor vs cpu-poor/mem-rich) under a mixed
cpu-heavy/mem-heavy ask wave with priority skew, the multi-dimensional
contention the greedy scalar score cannot see (PAPERS.md: CvxCluster's
granular-allocation LP, POP's partitioned subproblems) — and A/Bs packed
utilization and warm plan latency.

Per shape prints one JSON line:
  {"pods": N, "nodes": M, "parts": K, "greedy_placed": ..., "pack_placed":
   ..., "util_ratio": ..., "greedy_warm_ms": ..., "pack_warm_ms": ...,
   "latency_ratio": ...}

--shapes 1024x128,4096x512     podsxnodes shapes (default three shapes)
--assert-quality               exit 1 unless on the LAST (largest) shape the
                               pack plan beats greedy packed units AND warm
                               plan latency stays within --max-latency-ratio
                               (the pack-smoke CI gate)
--max-latency-ratio 2.0        acceptance bound for pack_warm/greedy_warm
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(n_pods: int, n_nodes: int, seed: int = 0):
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    rng = random.Random(seed)
    cache = SchedulerCache()
    for i in range(n_nodes):
        # fragmented fleet: two node flavors with opposite headroom shapes
        if i % 2 == 0:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=8000,
                                        memory=4 * 2**30))
        else:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=2000,
                                        memory=16 * 2**30))
    pods = []
    for k in range(n_pods):
        if rng.random() < 0.5:
            pods.append(make_pod(f"p{k}", cpu_milli=1900, memory=2**28,
                                 priority=rng.choice([0, 5])))
        else:
            pods.append(make_pod(f"p{k}", cpu_milli=300, memory=3 * 2**30,
                                 priority=rng.choice([0, 5])))
    import numpy as np

    # priorities reach BOTH solvers: the asks carry them, and the ranks
    # replicate the gate's priority-desc-then-FIFO order, so the bench A/B
    # (and choose_plan's priority guard) exercises the skew production sees
    asks = [AllocationAsk(p.uid, "pack-app", get_pod_resource(p),
                          priority=p.spec.priority or 0, pod=p)
            for p in pods]
    priorities = np.asarray([p.spec.priority or 0 for p in pods])
    order = np.lexsort((np.arange(len(pods)), -priorities))
    ranks = np.empty(len(pods), np.int64)
    ranks[order] = np.arange(len(pods))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return enc, enc.build_batch(asks, ranks=ranks.tolist()), priorities


def run_shape(n_pods: int, n_nodes: int) -> dict:
    import numpy as np

    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import solve_batch

    enc, batch, priorities = build(n_pods, n_nodes)

    def greedy():
        r = solve_batch(batch, enc.nodes)
        return np.asarray(r.assigned)[: batch.num_pods]

    def pack():
        r = pack_mod.pack_solve_batch(batch, enc.nodes, seed=7)
        return np.asarray(r.assigned)[: batch.num_pods], r.n_parts

    ga = greedy()                        # cold (trace+compile)
    t0 = time.time()
    ga = greedy()
    greedy_ms = (time.time() - t0) * 1000
    pa, parts = pack()                   # cold
    t0 = time.time()
    pa, parts = pack()
    pack_ms = (time.time() - t0) * 1000

    # the production decision rule: priority-guarded, capacity-normalized
    use_pack, st = pack_mod.choose_plan(
        ga, pa, batch.req.astype(np.int32), batch.valid,
        cap_i=np.floor(enc.nodes.capacity_arr).astype(np.int64),
        priorities=np.asarray(priorities))
    return {
        "pods": n_pods,
        "nodes": n_nodes,
        "parts": parts,
        "greedy_placed": st["greedy"]["placed"],
        "pack_placed": st["pack"]["placed"],
        "greedy_units": st["greedy"]["units"],
        "pack_units": st["pack"]["units"],
        "pack_wins": bool(use_pack),
        # the SAME quantity the core's pack_util/pack_last_util reports:
        # capacity-normalized packed units, pack/greedy — the bench gate
        # must agree with the decision rule it exercises
        "util_ratio": round(st["pack"]["units_norm"]
                            / max(st["greedy"]["units_norm"], 1e-9), 4),
        "greedy_warm_ms": round(greedy_ms, 1),
        "pack_warm_ms": round(pack_ms, 1),
        "latency_ratio": round(pack_ms / max(greedy_ms, 1e-6), 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="1024x128,2048x256,4096x512")
    ap.add_argument("--assert-quality", action="store_true",
                    help="exit 1 unless the last shape's pack plan beats "
                         "greedy packed units within the latency bound")
    ap.add_argument("--max-latency-ratio", type=float, default=2.0)
    args = ap.parse_args()

    last = None
    for shape in args.shapes.split(","):
        n_pods, n_nodes = (int(x) for x in shape.strip().split("x"))
        last = run_shape(n_pods, n_nodes)
        print(json.dumps(last), flush=True)

    if args.assert_quality and last is not None:
        if not last["pack_wins"] or last["util_ratio"] <= 1.0:
            print(f"FAIL: pack plan did not beat greedy on the "
                  f"{last['pods']}x{last['nodes']} shape "
                  f"(util_ratio {last['util_ratio']})", file=sys.stderr)
            return 1
        if last["latency_ratio"] > args.max_latency_ratio:
            print(f"FAIL: warm pack plan latency {last['pack_warm_ms']}ms is "
                  f"{last['latency_ratio']}x greedy "
                  f"(bound {args.max_latency_ratio}x)", file=sys.stderr)
            return 1
        print(f"OK: pack beats greedy (util_ratio {last['util_ratio']}, "
              f"latency {last['latency_ratio']}x <= "
              f"{args.max_latency_ratio}x)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
