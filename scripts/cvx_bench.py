#!/usr/bin/env python
"""CvxCluster-arm microbench: ONE full-fleet convex relaxation vs the
POP-partitioned pack LP vs the greedy argmin (and the learned arm when a
checkpoint is supplied).

Builds the contended shapes the global solve exists for — the pack bench's
fragmented two-flavor fleet under a priority-skewed mixed ask wave, plus
optional gang groups (--gang G tags every G consecutive asks as one
all-or-nothing task group) — and A/Bs packed utilization and warm plan
latency through the production decision rule (choose_plan_n, priority
guards, capacity-normalized units).

Per shape prints one JSON line:
  {"pods": N, "nodes": M, "gang": G, "winner": ...,
   "greedy_placed"/"pack_placed"/"cvx_placed"/"learned_placed": ...,
   "greedy_units"/"pack_units"/"cvx_units"/"learned_units": ...,
   "cvx_util": cvx/greedy normalized units, "cvx_iters": fixed trip count,
   "greedy_warm_ms"/"pack_warm_ms"/"cvx_solve_ms": ...,
   "latency_ratio": cvx_warm/pack_warm}

--shapes 2048x1024,4096x4096   podsxnodes (default: the PERF round-19 set;
                               N*M must clear the cvx cell budget)
--gang 8                       pods per gang group (0 = no gangs)
--checkpoint PREFIX            two-tower checkpoint: adds the learned arm
                               AND warm-starts the cvx dual from it
--assert-quality               exit 1 unless on the LAST shape the cvx arm
                               wins the duel with strictly more packed
                               units than every arm in --beat, within
                               --max-latency-ratio of the pack solve
--beat greedy,pack,learned     arms cvx must strictly out-pack (the ISSUE's
                               gang acceptance is greedy,learned — the pack
                               arm may tie the relaxation on saturating
                               shapes)
--max-latency-ratio 3.0        acceptance bound for cvx_warm/pack_warm
                               (<= 0 disables: the dense solve's cost grows
                               with N*M while the partitioned pack solve's
                               does not — the bound is a smoke-shape check)
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pack_bench import build as _pack_build  # noqa: E402


def build(n_pods: int, n_nodes: int, gang: int = 0, seed: int = 0):
    """The pack bench's fragmented fleet + priority-skewed wave, rebuilt
    with gang tags when requested (the batch encoder folds a task group
    into one all-or-nothing constraint group)."""
    if gang <= 1:
        return _pack_build(n_pods, n_nodes, seed=seed)
    import numpy as np

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    import random as _random

    rng = _random.Random(seed)
    cache = SchedulerCache()
    for i in range(n_nodes):
        if i % 2 == 0:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=8000,
                                        memory=4 * 2**30))
        else:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=2000,
                                        memory=16 * 2**30))
    pods = []
    for k in range(n_pods):
        if rng.random() < 0.5:
            pods.append(make_pod(f"p{k}", cpu_milli=1900, memory=2**28,
                                 priority=rng.choice([0, 5])))
        else:
            pods.append(make_pod(f"p{k}", cpu_milli=300, memory=3 * 2**30,
                                 priority=rng.choice([0, 5])))
    asks = []
    for k, p in enumerate(pods):
        ask = AllocationAsk(p.uid, "cvx-app", get_pod_resource(p),
                            priority=p.spec.priority or 0, pod=p)
        ask.task_group_name = f"tg{k // gang}"
        asks.append(ask)
    priorities = np.asarray([p.spec.priority or 0 for p in pods])
    order = np.lexsort((np.arange(len(pods)), -priorities))
    ranks = np.empty(len(pods), np.int64)
    ranks[order] = np.arange(len(pods))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return enc, enc.build_batch(asks, ranks=ranks.tolist()), priorities


def run_shape(n_pods: int, n_nodes: int, gang: int = 0,
              checkpoint: str = "") -> dict:
    import numpy as np

    from yunikorn_tpu.ops import cvx_solve as cvx_mod
    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import solve_batch

    enc, batch, priorities = build(n_pods, n_nodes, gang=gang)
    n = batch.num_pods

    learned_params = None
    ck_hash = ""
    if checkpoint:
        from yunikorn_tpu.policy import net as pnet

        ck = pnet.load_checkpoint(checkpoint)
        learned_params, ck_hash = ck.params, ck.hash

    def greedy():
        return np.asarray(solve_batch(batch, enc.nodes).assigned)[:n]

    def pack():
        return np.asarray(pack_mod.pack_solve_batch(
            batch, enc.nodes, seed=7).assigned)[:n]

    def cvx():
        r = cvx_mod.cvx_solve_batch(batch, enc.nodes, seed=7,
                                    learned=learned_params,
                                    aot_extra=(("policy", ck_hash)
                                               if ck_hash else ()))
        return np.asarray(r.assigned)[:n], r

    ga = greedy()                        # cold (trace+compile)
    t0 = time.time()
    ga = greedy()
    greedy_ms = (time.time() - t0) * 1000
    pa = pack()                          # cold
    t0 = time.time()
    pa = pack()
    pack_ms = (time.time() - t0) * 1000
    ca, cres = cvx()                     # cold
    t0 = time.time()
    ca, cres = cvx()
    cvx_ms = (time.time() - t0) * 1000
    assert bool(np.asarray(cres.feasible)), "cvx emitted an infeasible plan"

    cands = [("greedy", ga), ("optimal", pa), ("cvx", ca)]
    if learned_params is not None:
        la = np.asarray(solve_batch(
            batch, enc.nodes,
            learned=(learned_params, 7)).assigned)[:n]     # cold
        la = np.asarray(solve_batch(
            batch, enc.nodes, learned=(learned_params, 7)).assigned)[:n]
        cands.append(("learned", la))

    winner, st = pack_mod.choose_plan_n(
        cands, batch.req.astype(np.int32), batch.valid,
        cap_i=np.floor(enc.nodes.capacity_arr).astype(np.int64),
        priorities=np.asarray(priorities))
    out = {
        "pods": n_pods,
        "nodes": n_nodes,
        "gang": gang,
        "winner": winner,
        "cvx_wins": winner == "cvx",
        # same quantity the core's cvx_last_util gauge reports
        "cvx_util": round(st["cvx"]["units_norm"]
                          / max(st["greedy"]["units_norm"], 1e-9), 4),
        "cvx_iters": cres.iters,
        "learned_dual": bool(cres.learned_dual),
        "greedy_warm_ms": round(greedy_ms, 1),
        "pack_warm_ms": round(pack_ms, 1),
        "cvx_solve_ms": round(cvx_ms, 1),
        "latency_ratio": round(cvx_ms / max(pack_ms, 1e-6), 2),
    }
    for name, _ in cands:
        out[f"{name.replace('optimal', 'pack')}_placed"] = st[name]["placed"]
        out[f"{name.replace('optimal', 'pack')}_units"] = st[name]["units"]
    return out


def quality_failures(last: dict, beat, max_latency_ratio: float) -> list:
    """Acceptance verdicts on one shape's JSON record (pure; unit-tested
    against recorded bench lines). Returns failure strings, empty = pass."""
    fails = []
    losers = [k for k in beat
              if f"{k}_units" in last
              and last[f"{k}_units"] >= last["cvx_units"]]
    if not last["cvx_wins"] or losers:
        fails.append(
            f"cvx did not strictly win the "
            f"{last['pods']}x{last['nodes']} duel (winner "
            f"{last['winner']}, not beaten: {losers or 'duel'})")
    if 0 < max_latency_ratio < last["latency_ratio"]:
        fails.append(
            f"warm cvx solve {last['cvx_solve_ms']}ms is "
            f"{last['latency_ratio']}x the pack solve "
            f"(bound {max_latency_ratio}x)")
    return fails


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="2048x1024,4096x4096")
    ap.add_argument("--gang", type=int, default=0,
                    help="pods per gang group (0 = no gangs)")
    ap.add_argument("--checkpoint", default="",
                    help="two-tower checkpoint prefix: adds the learned "
                         "arm and warm-starts the cvx dual")
    ap.add_argument("--assert-quality", action="store_true",
                    help="exit 1 unless the last shape's cvx plan wins the "
                         "duel strictly within the latency bound")
    ap.add_argument("--beat", default="greedy,pack,learned",
                    help="arms the cvx plan must strictly out-pack")
    ap.add_argument("--max-latency-ratio", type=float, default=3.0,
                    help="cvx_warm/pack_warm acceptance bound; <= 0 disables")
    args = ap.parse_args()

    last = None
    for shape in args.shapes.split(","):
        n_pods, n_nodes = (int(x) for x in shape.strip().split("x"))
        last = run_shape(n_pods, n_nodes, gang=args.gang,
                         checkpoint=args.checkpoint)
        print(json.dumps(last), flush=True)

    if args.assert_quality and last is not None:
        beat = [b for b in args.beat.split(",") if b]
        fails = quality_failures(last, beat, args.max_latency_ratio)
        if fails:
            for f in fails:
                print(f"FAIL: {f}", file=sys.stderr)
            return 1
        print(f"OK: cvx wins the duel (util {last['cvx_util']}, latency "
              f"{last['latency_ratio']}x, bound {args.max_latency_ratio}x)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
