#!/usr/bin/env python
"""Preemption-planner microbench: batched device solve vs host oracle.

Builds a pressure scenario that is representative of real preemption waves —
victims are SPARSE (only the tail ~2% of nodes hold preemptable pods), so the
host planner's per-ask candidate walk traverses nearly the whole node table
before finding its 32 searchable nodes, while the device planner evaluates
every node in one jitted dispatch. This is exactly the shape where the
per-entity host loop collapses at cluster scale (PAPERS.md: CvxCluster, POP).

Per size prints one JSON line:
  {"nodes": N, "asks": A, "host_ms": ..., "device_cold_ms": ...,
   "device_warm_ms": ..., "speedup_warm": ...}

--sizes 1024,5120,20480   node counts (default "512,4096")
--assert-speedup N        exit 1 unless device_warm < host at every size >= N
                          (the preempt-smoke CI gate)
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(n_nodes: int, n_asks: int, victim_frac: float = 0.02):
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    rng = random.Random(1234)
    cache = SchedulerCache()
    app_of_pod = {}
    victim_nodes = max(int(n_nodes * victim_frac), 4)
    for i in range(n_nodes):
        cache.update_node(make_node(f"n{i:05d}", cpu_milli=4000,
                                    memory=8 * 2**30))
        if i >= n_nodes - victim_nodes:
            for j in range(4):
                v = make_pod(f"v-{i}-{j}", cpu_milli=1000, memory=2**28,
                             node_name=f"n{i:05d}", phase="Running",
                             priority=rng.choice([0, 1, 2]))
                v.metadata.creation_timestamp = 1000.0 + rng.random() * 100
                cache.update_pod(v)
                app_of_pod[v.uid] = "victim-app"
    asks = []
    for k in range(n_asks):
        p = make_pod(f"hi-{k}", cpu_milli=2000, memory=2**28, priority=100)
        cache.update_pod(p)
        asks.append(AllocationAsk(p.uid, "hi-app", get_pod_resource(p),
                                  priority=100, pod=p))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return cache, enc, asks, app_of_pod


def run_size(n_nodes: int, n_asks: int) -> dict:
    from yunikorn_tpu.core.preemption import (
        plan_preemptions,
        plan_preemptions_batched,
    )

    cache, enc, asks, app_of_pod = build(n_nodes, n_asks)
    cands = list(cache.node_names())

    t0 = time.time()
    host_plans, _ = plan_preemptions(cache, asks, app_of_pod,
                                     candidate_nodes=cands)
    host_ms = (time.time() - t0) * 1000

    # cold: full victim-table sync + kernel trace/compile at this bucket
    t0 = time.time()
    dev_plans, _, _ = plan_preemptions_batched(cache, enc, asks, app_of_pod,
                                               candidate_nodes=cands)
    cold_ms = (time.time() - t0) * 1000
    # warm: tables synced, program compiled — the steady-state pressure cycle
    t0 = time.time()
    dev_plans, _, stats = plan_preemptions_batched(cache, enc, asks,
                                                   app_of_pod,
                                                   candidate_nodes=cands)
    warm_ms = (time.time() - t0) * 1000

    hk = [(p.ask.allocation_key, p.node_id, [v.uid for v in p.victims])
          for p in host_plans]
    dk = [(p.ask.allocation_key, p.node_id, [v.uid for v in p.victims])
          for p in dev_plans]
    assert hk == dk, f"planner divergence at {n_nodes} nodes"
    return {
        "nodes": n_nodes,
        "asks": n_asks,
        "plans": len(dev_plans),
        "host_ms": round(host_ms, 1),
        "device_cold_ms": round(cold_ms, 1),
        "device_warm_ms": round(warm_ms, 1),
        "speedup_warm": round(host_ms / max(warm_ms, 1e-6), 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="512,4096")
    ap.add_argument("--asks", type=int, default=16)
    ap.add_argument("--assert-speedup", type=int, default=0, metavar="N",
                    help="fail unless device_warm < host at sizes >= N")
    args = ap.parse_args()

    failures = []
    for size in [int(s) for s in args.sizes.split(",") if s]:
        row = run_size(size, args.asks)
        print(json.dumps(row), flush=True)
        if (args.assert_speedup and size >= args.assert_speedup
                and row["device_warm_ms"] >= row["host_ms"]):
            failures.append(row)
    if failures:
        print(f"# FAIL: device planner slower than host oracle at "
              f"{[r['nodes'] for r in failures]} nodes", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
