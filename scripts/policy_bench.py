#!/usr/bin/env python
"""Learned-policy A/B: train-then-solve round trip on a seeded fragmented
trace, gating the round-17 acceptance criteria.

The scenario is the pack-bench fragmentation shape — two node flavors with
opposite headroom (cpu-rich/mem-poor vs cpu-poor/mem-rich) under a mixed
cpu-heavy/mem-heavy ask wave with priority skew — exactly where the greedy
scalar score strands capacity and the round-12 LP pack solver wins. The
bench:

  1. records a duel DATASET at the training shape: each seeded cycle runs
     the greedy solve and the pack solve, the production `choose_plan`
     decides the winner, and the cycle is written in the exact
     CoreScheduler.policy_recorder format trace_replay --dataset-out uses
     (policy/train.py is the single source for both);
  2. trains a checkpoint from it (imitation of the duel winners +
     packed-units fine-tune) — or loads one via --checkpoint;
  3. evaluates at the EVAL shape (default 512 pods x 4096 nodes, the
     acceptance scale): the learned-arm solve vs the greedy solve on a
     fresh seeded cycle, compared with the solver's capacity-normalized
     packed units;
  4. proves the safety floor: an UNTRAINED (zero output layer) checkpoint
     must produce a plan bit-identical to greedy's.

--assert-quality (the policy-smoke CI gate) exits nonzero unless, at the
eval shape: learned packed units >= --min-win x greedy's (default 1.05 —
the ">= 5%% win" acceptance bar), learned placements >= greedy placements
(zero placement loss), AND the untrained arm is bit-identical to greedy.
"""
import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(n_pods: int, n_nodes: int, seed: int = 0):
    """Seeded fragmented-fleet cycle (the pack_bench shape + priorities)."""
    import numpy as np

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    rng = random.Random(seed)
    cache = SchedulerCache()
    for i in range(n_nodes):
        if i % 2 == 0:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=8000,
                                        memory=4 * 2**30))
        else:
            cache.update_node(make_node(f"n{i:05d}", cpu_milli=2000,
                                        memory=16 * 2**30))
    pods = []
    for k in range(n_pods):
        if rng.random() < 0.5:
            pods.append(make_pod(f"p{k}", cpu_milli=1900, memory=2**28,
                                 priority=rng.choice([0, 5])))
        else:
            pods.append(make_pod(f"p{k}", cpu_milli=300, memory=3 * 2**30,
                                 priority=rng.choice([0, 5])))
    asks = [AllocationAsk(p.uid, "policy-app", get_pod_resource(p),
                          priority=p.spec.priority or 0, pod=p)
            for p in pods]
    priorities = np.asarray([p.spec.priority or 0 for p in pods])
    order = np.lexsort((np.arange(len(pods)), -priorities))
    ranks = np.empty(len(pods), np.int64)
    ranks[order] = np.arange(len(pods))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    return enc, enc.build_batch(asks, ranks=ranks.tolist()), priorities


def record_cycle(enc, batch, priorities, writer) -> str:
    """One greedy-vs-pack duel, recorded in the policy_recorder format."""
    import numpy as np

    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import solve_batch

    ga = np.asarray(solve_batch(batch, enc.nodes).assigned)[:batch.num_pods]
    pa = np.asarray(pack_mod.pack_solve_batch(
        batch, enc.nodes, seed=7).assigned)[:batch.num_pods]
    cap = np.floor(enc.nodes.capacity_arr).astype(np.int64)
    winner, _ = pack_mod.choose_plan_n(
        [("greedy", ga), ("optimal", pa)], batch.req.astype(np.int32),
        batch.valid, cap_i=cap, priorities=priorities)
    na = enc.nodes
    writer({
        "req": batch.req.astype(np.int32),
        "rank": np.asarray(batch.rank),
        "valid": np.asarray(batch.valid),
        "free0": np.floor(na.free).astype(np.int32),
        "cap": cap.astype(np.int32),
        "node_ok": np.asarray(na.valid & na.schedulable),
        "priorities": priorities,
        "score_cols": int(batch.req.shape[1]),
        "winner": winner,
        "plan_greedy": ga,
        "plan_optimal": pa,
    })
    return winner


def evaluate(params, untrained, n_pods: int, n_nodes: int, seed: int) -> dict:
    import numpy as np

    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import solve_batch

    enc, batch, priorities = build(n_pods, n_nodes, seed=seed)
    n = batch.num_pods

    def timed(fn):
        fn()                       # cold (trace+compile or store load)
        t0 = time.time()
        out = fn()
        return out, (time.time() - t0) * 1000

    ga, greedy_ms = timed(lambda: np.asarray(
        solve_batch(batch, enc.nodes).assigned)[:n])
    la, learned_ms = timed(lambda: np.asarray(
        solve_batch(batch, enc.nodes, learned=(params, 1)).assigned)[:n])
    ua = np.asarray(solve_batch(
        batch, enc.nodes, learned=(untrained, 1)).assigned)[:n]
    cap = np.floor(enc.nodes.capacity_arr).astype(np.int64)
    req_i = batch.req.astype(np.int32)
    winner, utils = pack_mod.choose_plan_n(
        [("greedy", ga), ("learned", la)], req_i, batch.valid,
        cap_i=cap, priorities=priorities)
    g, l = utils["greedy"], utils["learned"]
    return {
        "pods": n_pods,
        "nodes": n_nodes,
        "greedy_placed": g["placed"],
        "learned_placed": l["placed"],
        "greedy_units_norm": round(g["units_norm"], 3),
        "learned_units_norm": round(l["units_norm"], 3),
        # the production decision rule's own verdict + the A/B headline
        "duel_winner": winner,
        "util_ratio": round(l["units_norm"] / max(g["units_norm"], 1e-9), 4),
        "greedy_warm_ms": round(greedy_ms, 1),
        "learned_warm_ms": round(learned_ms, 1),
        "latency_ratio": round(learned_ms / max(greedy_ms, 1e-6), 2),
        "untrained_bit_identical": bool(np.array_equal(ua, ga)),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--train", action="store_true",
                    help="record a dataset + train a checkpoint first "
                         "(otherwise --checkpoint must point at one)")
    ap.add_argument("--checkpoint", default="",
                    help="existing checkpoint prefix to evaluate")
    ap.add_argument("--out", default="",
                    help="checkpoint prefix to write when --train "
                         "(default: a temp dir)")
    ap.add_argument("--dataset-out", default="",
                    help="keep the recorded dataset here when --train")
    ap.add_argument("--train-shape", default="256x128",
                    help="podsxnodes of each recorded training cycle")
    ap.add_argument("--train-cycles", type=int, default=4)
    ap.add_argument("--pods", type=int, default=512)
    ap.add_argument("--nodes", type=int, default=4096,
                    help="eval node count (the acceptance gate runs 4k+)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--eval-seed", type=int, default=99)
    ap.add_argument("--imitation-epochs", type=int, default=60)
    ap.add_argument("--finetune-epochs", type=int, default=40)
    ap.add_argument("--min-win", type=float, default=1.05,
                    help="required learned/greedy packed-units ratio "
                         "(1.05 = the >=5%% acceptance bar)")
    ap.add_argument("--assert-quality", action="store_true",
                    help="exit 1 unless the win/no-loss/bit-identity gates "
                         "all hold (the policy-smoke CI gate)")
    args = ap.parse_args()

    from yunikorn_tpu.policy import net as pnet
    from yunikorn_tpu.policy import train as ptrain

    if args.train:
        ds_dir = args.dataset_out or os.path.join(
            tempfile.mkdtemp(prefix="yk_policy_"), "ds")
        writer = ptrain.DatasetWriter(ds_dir)
        tp, tn = (int(x) for x in args.train_shape.split("x"))
        t0 = time.time()
        winners = {}
        for c in range(args.train_cycles):
            enc, batch, pr = build(tp, tn, seed=args.seed + c)
            w = record_cycle(enc, batch, pr, writer)
            winners[w] = winners.get(w, 0) + 1
        print(f"[policy-bench] recorded {writer.written} cycles at "
              f"{args.train_shape} in {time.time() - t0:.1f}s "
              f"(winners: {winners})", file=sys.stderr, flush=True)
        t0 = time.time()
        params, report = ptrain.fit(
            ptrain.load_dataset(ds_dir), seed=args.seed,
            imitation_epochs=args.imitation_epochs,
            finetune_epochs=args.finetune_epochs)
        print(f"[policy-bench] trained in {time.time() - t0:.1f}s: "
              f"{report}", file=sys.stderr, flush=True)
        out = args.out or os.path.join(os.path.dirname(ds_dir), "ck")
        ck = pnet.save_checkpoint(
            out, params, epoch=args.imitation_epochs + args.finetune_epochs,
            meta={"bench": True, "train_shape": args.train_shape,
                  "cycles": writer.written})
        print(f"[policy-bench] checkpoint {out} (hash {ck.hash})",
              file=sys.stderr, flush=True)
    elif args.checkpoint:
        ck = pnet.load_checkpoint(args.checkpoint)
        params = ck.params
    else:
        print("FAIL: pass --train or --checkpoint", file=sys.stderr)
        return 2

    result = evaluate(params, pnet.init_params(args.seed + 1),
                      args.pods, args.nodes, args.eval_seed)
    result["checkpoint_hash"] = ck.hash
    print(json.dumps(result), flush=True)

    if args.assert_quality:
        ok = True
        if result["util_ratio"] < args.min_win:
            print(f"FAIL: learned/greedy packed-units ratio "
                  f"{result['util_ratio']} < required {args.min_win}",
                  file=sys.stderr)
            ok = False
        if result["learned_placed"] < result["greedy_placed"]:
            print(f"FAIL: learned arm lost placements "
                  f"({result['learned_placed']} < "
                  f"{result['greedy_placed']})", file=sys.stderr)
            ok = False
        if not result["untrained_bit_identical"]:
            print("FAIL: untrained checkpoint's plan is not bit-identical "
                  "to greedy", file=sys.stderr)
            ok = False
        if not ok:
            return 1
        print(f"OK: learned beats greedy {result['util_ratio']}x packed "
              f"units at {result['nodes']} nodes with zero placement loss "
              f"({result['learned_placed']} vs {result['greedy_placed']} "
              f"placed); untrained arm bit-identical to greedy",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
