#!/usr/bin/env python
"""Offline AOT-store builder: compile + serialize the solver executables for
the standard bucket ladder, so production processes start solve-ready.

For each NODESxPODS bucket this builder populates the store two ways:

  variants — jaxtools.warm_bucket compile_only coverage: both nodesort
             policies x plain and soft/locality batch variants of
             assign.solve / solve_chunked (the same variant matrix
             --prewarm warms, now persisted instead of re-traced per
             process).
  cycles   — a REAL CoreScheduler trace at the bucket (the same synthetic
             cluster shape bench.py and scripts/aot_smoke.py drive): two
             scheduling cycles + release, so every jitted program a
             production first cycle dispatches (gate/encode/solve) lands in
             the store with exactly the fingerprint production will compute.
             --with-preempt adds a preemption-pressure probe (the batched
             victim-selection solve); --policy optimal adds the pack solver.

The jax persistent-cache entries written during the build are mirrored into
the store (store/xla_cache/) and restored by consumers before their first
compile — the local half of the relay cache gap.

The store is keyed by (jax/jaxlib version, backend platform + device count,
shapes, dtype mode, solver statics): build on the SAME software + topology
the consumer runs, e.g. on CPU for the CPU smoke, on the TPU host for
production. Run with JAX_PLATFORMS=cpu for a CPU store.

Usage:
  python scripts/aot_build.py --store DIR [--buckets 1024x4096,...]
      [--no-variants] [--no-cycles] [--with-preempt] [--policy optimal]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BUCKETS = "1024x4096"


def run_trace(n_nodes: int, n_pods: int, *, policy: str = "greedy",
              preempt: bool = False, cycles: int = 2):
    """Drive a real CoreScheduler against the synthetic bench-shaped cluster
    (make_kwok_nodes / make_sleep_pods, 5 queues — the same generators
    bench.py uses) for `cycles` full-bucket scheduling cycles.

    Returns {"placements": {alloc_key: node}, "first_cycle_ms", "steady_ms",
    "scheduled"}. Shared by the builder (to compile every program a first
    cycle dispatches) and scripts/aot_smoke.py (to prove a store-hit first
    cycle is placement-identical to a cold-compiled one) — one driver, so
    the built fingerprints are exactly the replayed ones.
    """
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationAsk,
        AllocationRelease,
        AllocationRequest,
        ApplicationRequest,
        NodeAction,
        NodeInfo,
        NodeRequest,
        RegisterResourceManagerRequest,
        TerminationType,
        UserGroupInfo,
    )
    from yunikorn_tpu.core.scheduler import CoreScheduler, SolverOptions

    placements = {}

    class Callback:
        def update_allocation(self, response):
            for alloc in getattr(response, "new", None) or []:
                placements[alloc.allocation_key] = alloc.node_id

        def __getattr__(self, name):
            if name == "get_state_dump":
                return lambda: "{}"
            return lambda *a, **k: None

    cache = SchedulerCache()
    so = SolverOptions()
    so.policy = "optimal" if policy == "optimal" else "greedy"
    core = CoreScheduler(cache, solver_options=so)
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="aot", policy_group="queues"),
        Callback())
    nodes = make_kwok_nodes(n_nodes)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    core.update_node(NodeRequest(nodes=infos))
    n_queues = 5
    for q in range(n_queues):
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id=f"aot-app-{q}", queue_name=f"root.q{q}",
            user=UserGroupInfo(user="aot"))]))
    pods = []
    for q in range(n_queues):
        pods.extend(make_sleep_pods(n_pods // n_queues, f"aot-app-{q}",
                                    queue=f"root.q{q}", name_prefix=f"aq{q}"))
    asks = [AllocationAsk(p.uid, p.metadata.labels["applicationId"],
                          get_pod_resource(p), pod=p) for p in pods]

    first_ms = steady_ms = 0.0
    scheduled = 0
    first_placements = None
    for c in range(max(cycles, 1)):
        core.update_allocation(AllocationRequest(asks=list(asks)))
        t0 = time.perf_counter()
        scheduled = core.schedule_once()
        dt = (time.perf_counter() - t0) * 1000
        if c == 0:
            first_ms = dt
            first_placements = dict(placements)
        steady_ms = dt
        core.update_allocation(AllocationRequest(releases=[
            AllocationRelease(a.application_id, a.allocation_key,
                              TerminationType.STOPPED_BY_RM) for a in asks]))
        core.schedule_once()
    if preempt:
        from yunikorn_tpu.common.objects import make_pod

        # cluster refilled so victims exist, then one unplaceable
        # high-priority ask drives the batched victim-selection solve
        core.update_allocation(AllocationRequest(asks=list(asks)))
        core.schedule_once()
        hp = make_pod("aot-preempt-probe", cpu_milli=10**9, priority=1000)
        core.update_allocation(AllocationRequest(asks=[AllocationAsk(
            hp.uid, "aot-app-0", get_pod_resource(hp), priority=1000,
            pod=hp)]))
        core.schedule_once()
    return {"placements": first_placements or {}, "first_cycle_ms": first_ms,
            "steady_ms": steady_ms, "scheduled": scheduled}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True,
                    help="AOT store directory (created if missing)")
    ap.add_argument("--buckets", default=DEFAULT_BUCKETS,
                    help="comma-separated NODESxPODS pairs")
    ap.add_argument("--no-variants", action="store_true",
                    help="skip the prewarm variant matrix (policies x "
                         "plain/locality)")
    ap.add_argument("--no-cycles", action="store_true",
                    help="skip the real-cycle trace (gate/encode coverage)")
    ap.add_argument("--with-preempt", action="store_true",
                    help="also build the preemption victim-selection solve")
    ap.add_argument("--policy", default="greedy",
                    choices=("greedy", "optimal"),
                    help="optimal also builds the pack solver executables")
    args = ap.parse_args()

    from yunikorn_tpu import aot
    from yunikorn_tpu.utils.jaxtools import (
        backend_or_cpu,
        ensure_compilation_cache,
        warm_bucket,
    )

    t0 = time.time()
    platform = backend_or_cpu()
    rt = aot.install(args.store)
    ensure_compilation_cache()

    built = []
    for pair in args.buckets.split(","):
        pair = pair.strip().lower()
        if not pair:
            continue
        n_nodes, n_pods = (int(x) for x in pair.split("x"))
        t_b = time.time()
        if not args.no_variants:
            warm_bucket(n_nodes, n_pods)
        if not args.no_cycles:
            run_trace(n_nodes, n_pods, policy=args.policy,
                      preempt=args.with_preempt)
        built.append({"bucket": pair, "secs": round(time.time() - t_b, 1)})
        print(f"# aot_build: bucket {pair} done in {built[-1]['secs']}s",
              file=sys.stderr, flush=True)

    rt.flush()  # join in-flight store writes before reading counts/exiting
    mirrored = rt.store.save_persistent_cache()
    out = {"store": os.path.abspath(args.store), "platform": platform,
           "buckets": built, "entries": rt.store.entry_count(),
           "persistent_cache_mirrored": mirrored, "aot": rt.stats(),
           "total_secs": round(time.time() - t0, 1)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
