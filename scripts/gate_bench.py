#!/usr/bin/env python
"""Admission-gate microbench: array-form vector_admit vs the legacy per-ask
loop, plus the churn-encode O(changed) check.

The trace models a real pending backlog: a three-level queue tree (quotas on
leaves AND a shared parent, user/group limits on a slice of it), asks spread
over the leaves from a handful of users. Three contention shapes (see
build_tree): default ~6% held (the north-star backlog that mostly fits),
--contended ~26% held, --saturated ~85% held. This is the shape where the
per-ask host loop collapses: every ask pays a quota-chain walk + limit scan
+ accumulator folds in pure Python, while the vector gate pays one lexsort
+ a few prefix-scan passes.

Per size prints one JSON line:
  {"asks": N, "legacy_ms": ..., "vector_ms": ..., "speedup": ...,
   "held": ..., "passes": ...}

--sizes 2000,20000,50000   ask counts (default "2000,20000")
--assert-speedup N         exit 1 unless vector beats legacy at every
                           size >= N (the gate-smoke CI gate)
--churn-check              also run the encoder churn check: a 1%-churn
                           cycle must re-encode only the changed rows
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_tree(n_asks, scale=1.3):
    """Quotas sized relative to the backlog's demand.

    scale=1.3 (default): ~6% of the backlog holds — the north-star shape
    (50k pending that mostly fit, the gate clips the tail). scale=1.0
    (--contended): ~26% holds, every (leaf, user) limit saturated — the
    multi-pass convergence shape. scale=0.2 (--saturated): ~85% holds, the
    adversarial worst case (the vector gate's prefix over-estimate defers
    the most asks per pass)."""
    from yunikorn_tpu.common.resource import Resource
    from yunikorn_tpu.core.queues import LimitConfig, QueueConfig, QueueTree

    # per-leaf demand: n/8 asks averaging ~283m cpu / ~320 memory units
    cpu_q = max(int(n_asks * 28.3 * scale), 1000)
    mem_q = max(int(n_asks * 32.0 * scale), 1000)
    leaves = []
    for i in range(8):
        cfg = QueueConfig(name=f"leaf{i}")
        cfg.max_resource = Resource({"cpu": cpu_q, "memory": mem_q})
        if i % 2 == 0:
            cfg.limits = [LimitConfig(
                users=["*"],
                max_resources=Resource({"cpu": max(int(cpu_q * 0.34), 500)}))]
        if i % 3 == 0:
            cfg.properties["priority.offset"] = str(i % 3)
        leaves.append(cfg)
    parents = [
        QueueConfig(name="pa", parent=True,
                    max_resource=Resource({"cpu": int(cpu_q * 3.4)}),
                    limits=[LimitConfig(groups=["dev"],
                                        max_resources=Resource(
                                            {"memory": int(mem_q * 2.3)}))],
                    children=leaves[:4]),
        QueueConfig(name="pb", parent=True, children=leaves[4:]),
    ]
    return QueueTree(QueueConfig(name="root", parent=True, children=parents))


def build_trace(tree, n_asks):
    from yunikorn_tpu.common.resource import Resource
    from yunikorn_tpu.common.si import AllocationAsk, UserGroupInfo

    class App:
        def __init__(self, user, groups, submit_time, queue_name):
            self.user = UserGroupInfo(user=user, groups=groups)
            self.submit_time = submit_time
            self.queue_name = queue_name

    rng = random.Random(42)
    leaves = [q.full_name for q in tree.leaves()]
    users = [("alice", ["dev"]), ("bob", ["dev", "ops"]), ("carol", [])]
    apps = {}
    by_queue = {}
    for i in range(n_asks):
        qname = leaves[i % len(leaves)]
        user, groups = users[i % len(users)]
        app = apps.setdefault(
            (qname, user), App(user, list(groups),
                               round(rng.random() * 100, 3), qname))
        ask = AllocationAsk(
            f"ask-{i}", "app",
            Resource({"cpu": rng.choice([100, 250, 500]),
                      "memory": rng.choice([128, 512])}),
            priority=rng.choice([0, 0, 0, 1, 5]), seq=i)
        by_queue.setdefault(qname, []).append((app, ask))
    return by_queue


def meta_for(tree, by_queue):
    from yunikorn_tpu.common.resource import Resource

    cap = Resource({"cpu": 10_000_000, "memory": 20_000_000})
    meta = {}
    for qname in by_queue:
        leaf = tree.resolve(qname, create=False)
        meta[qname] = (leaf,
                       leaf.dominant_share(cap) if leaf else 0.0,
                       leaf.priority_adjustment() if leaf else 0)
    return meta


def bench_size(n_asks, repeats=3, scale=1.3):
    from yunikorn_tpu.core.gate import legacy_admit, vector_admit

    tree = build_tree(n_asks, scale=scale)
    by_queue = build_trace(tree, n_asks)
    meta = meta_for(tree, by_queue)

    def run(fn):
        best = float("inf")
        out = None
        for _ in range(repeats):
            trace = {q: list(v) for q, v in by_queue.items()}
            t0 = time.perf_counter()
            out = fn(trace)
            best = min(best, (time.perf_counter() - t0) * 1000)
        return best, out

    legacy_ms, (l_adm, l_held) = run(
        lambda tr: legacy_admit(tr, meta, tree))
    vector_ms, (v_adm, v_held, stats) = run(
        lambda tr: vector_admit(tr, meta, tree))
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm], "vector gate diverged from legacy"
    assert v_held == l_held, (v_held, l_held)
    return {
        "asks": n_asks,
        "legacy_ms": round(legacy_ms, 2),
        "vector_ms": round(vector_ms, 2),
        "speedup": round(legacy_ms / max(vector_ms, 1e-9), 2),
        "held": v_held,
        "passes": stats.get("passes"),
        "rank_ms": round(stats.get("rank_ms", 0.0), 2),
        "admit_ms": round(stats.get("admit_ms", 0.0), 2),
    }


def churn_check(n_pods=2000, churn=0.01):
    """1%-churn contract: the second encode re-derives only changed rows."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(64):
        cache.update_node(make_node(f"n{i}", cpu_milli=64000,
                                    memory=128 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=100) for i in range(n_pods)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]
    t0 = time.perf_counter()
    enc.build_batch(asks)
    cold_ms = (time.perf_counter() - t0) * 1000
    n_changed = max(int(n_pods * churn), 1)
    for i in range(n_changed):
        p = make_pod(f"p{i}", cpu_milli=700)
        asks[i] = AllocationAsk(asks[i].allocation_key, "app",
                                get_pod_resource(p), pod=p,
                                seq=n_pods + i)
    t0 = time.perf_counter()
    enc.build_batch(asks)
    churn_ms = (time.perf_counter() - t0) * 1000
    out = {
        "pods": n_pods,
        "changed": n_changed,
        "rows_reencoded": enc.last_encode_rows_reencoded,
        "cold_encode_ms": round(cold_ms, 2),
        "churn_encode_ms": round(churn_ms, 2),
    }
    print(json.dumps(out), flush=True)
    assert enc.last_encode_rows_reencoded == n_changed, \
        (enc.last_encode_rows_reencoded, n_changed)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="2000,20000")
    ap.add_argument("--assert-speedup", type=int, default=0, metavar="N",
                    help="exit 1 unless vector beats legacy at sizes >= N")
    ap.add_argument("--churn-check", action="store_true")
    ap.add_argument("--contended", action="store_true",
                    help="quotas at ~80%% of demand (~26%% held): every "
                         "(leaf, user) limit saturated")
    ap.add_argument("--saturated", action="store_true",
                    help="quotas at ~16%% of demand (~85%% held): the "
                         "adversarial multi-pass convergence shape")
    args = ap.parse_args()

    scale = 0.2 if args.saturated else (1.0 if args.contended else 1.3)
    failed = False
    for size in (int(s) for s in args.sizes.split(",") if s):
        r = bench_size(size, scale=scale)
        print(json.dumps(r), flush=True)
        if args.assert_speedup and size >= args.assert_speedup \
                and r["speedup"] <= 1.0:
            print(f"# FAIL: vector gate did not beat the legacy loop at "
                  f"{size} asks ({r['speedup']}x)", file=sys.stderr)
            failed = True
    if args.churn_check:
        churn_check()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
