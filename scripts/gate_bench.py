#!/usr/bin/env python
"""Admission-gate microbench: the jitted device scan vs array-form
vector_admit vs the legacy per-ask loop, plus the churn-encode O(changed)
check.

The trace models a real pending backlog: a three-level queue tree (quotas on
leaves AND a shared parent, user/group limits on a slice of it), asks spread
over the leaves from a handful of users. Three contention shapes (see
build_tree): default ~6% held (the north-star backlog that mostly fits),
--contended ~26% held, --saturated ~85% held. The saturated shape is the
device scan's reason to exist: the host scan's pass count is data-dependent
(~13 there), the device scan's is bounded ceil(log2(n))+C by construction.

Per size prints one JSON line:
  {"asks": N, "legacy_ms": ..., "vector_ms": ..., "device_ms": ...,
   "speedup": ..., "device_speedup": ..., "held": ..., "passes": ...,
   "device_passes": ..., "max_passes": ...}

--sizes 2000,20000,50000   ask counts (default "2000,20000")
--assert-speedup N         exit 1 unless vector beats legacy at every
                           size >= N (the gate-smoke CI gate)
--device                   also run (and report) the jitted device scan
--passes                   print a pass report per size and assert the
                           device pass count stays within its log-depth
                           bound (implies --device; the gate-device-smoke
                           CI gate — the saturated shape must complete in
                           <= ceil(log2(n))+C passes, never a
                           data-dependent blowup)
--churn-check              also run the encoder churn check: a 1%-churn
                           cycle must re-encode only the changed rows
--device-churn-check       the device row store analog: a 1%-churn cycle
                           must UPLOAD only the changed rows
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_tree(n_asks, scale=1.3):
    """Quotas sized relative to the backlog's demand.

    scale=1.3 (default): ~6% of the backlog holds — the north-star shape
    (50k pending that mostly fit, the gate clips the tail). scale=1.0
    (--contended): ~26% holds, every (leaf, user) limit saturated — the
    multi-pass convergence shape. scale=0.2 (--saturated): ~85% holds, the
    adversarial worst case (the vector gate's prefix over-estimate defers
    the most asks per pass)."""
    from yunikorn_tpu.common.resource import Resource
    from yunikorn_tpu.core.queues import LimitConfig, QueueConfig, QueueTree

    # per-leaf demand: n/8 asks averaging ~283m cpu / ~320 memory units
    cpu_q = max(int(n_asks * 28.3 * scale), 1000)
    mem_q = max(int(n_asks * 32.0 * scale), 1000)
    leaves = []
    for i in range(8):
        cfg = QueueConfig(name=f"leaf{i}")
        cfg.max_resource = Resource({"cpu": cpu_q, "memory": mem_q})
        if i % 2 == 0:
            cfg.limits = [LimitConfig(
                users=["*"],
                max_resources=Resource({"cpu": max(int(cpu_q * 0.34), 500)}))]
        if i % 3 == 0:
            cfg.properties["priority.offset"] = str(i % 3)
        leaves.append(cfg)
    parents = [
        QueueConfig(name="pa", parent=True,
                    max_resource=Resource({"cpu": int(cpu_q * 3.4)}),
                    limits=[LimitConfig(groups=["dev"],
                                        max_resources=Resource(
                                            {"memory": int(mem_q * 2.3)}))],
                    children=leaves[:4]),
        QueueConfig(name="pb", parent=True, children=leaves[4:]),
    ]
    return QueueTree(QueueConfig(name="root", parent=True, children=parents))


def build_trace(tree, n_asks):
    from yunikorn_tpu.common.resource import Resource
    from yunikorn_tpu.common.si import AllocationAsk, UserGroupInfo

    class App:
        def __init__(self, user, groups, submit_time, queue_name):
            self.user = UserGroupInfo(user=user, groups=groups)
            self.submit_time = submit_time
            self.queue_name = queue_name

    rng = random.Random(42)
    leaves = [q.full_name for q in tree.leaves()]
    users = [("alice", ["dev"]), ("bob", ["dev", "ops"]), ("carol", [])]
    apps = {}
    by_queue = {}
    for i in range(n_asks):
        qname = leaves[i % len(leaves)]
        user, groups = users[i % len(users)]
        app = apps.setdefault(
            (qname, user), App(user, list(groups),
                               round(rng.random() * 100, 3), qname))
        ask = AllocationAsk(
            f"ask-{i}", "app",
            Resource({"cpu": rng.choice([100, 250, 500]),
                      "memory": rng.choice([128, 512])}),
            priority=rng.choice([0, 0, 0, 1, 5]), seq=i)
        by_queue.setdefault(qname, []).append((app, ask))
    return by_queue


def meta_for(tree, by_queue):
    from yunikorn_tpu.common.resource import Resource

    cap = Resource({"cpu": 10_000_000, "memory": 20_000_000})
    meta = {}
    for qname in by_queue:
        leaf = tree.resolve(qname, create=False)
        meta[qname] = (leaf,
                       leaf.dominant_share(cap) if leaf else 0.0,
                       leaf.priority_adjustment() if leaf else 0)
    return meta


def bench_size(n_asks, repeats=3, scale=1.3, device=False):
    from yunikorn_tpu.core.gate import (
        extract_problem, legacy_admit, vector_admit)

    tree = build_tree(n_asks, scale=scale)
    by_queue = build_trace(tree, n_asks)
    meta = meta_for(tree, by_queue)

    def run(fn, warm=0):
        best = float("inf")
        out = None
        for rep in range(repeats + warm):
            trace = {q: list(v) for q, v in by_queue.items()}
            t0 = time.perf_counter()
            out = fn(trace)
            if rep >= warm:
                best = min(best, (time.perf_counter() - t0) * 1000)
        return best, out

    legacy_ms, (l_adm, l_held) = run(
        lambda tr: legacy_admit(tr, meta, tree))
    vector_ms, (v_adm, v_held, stats) = run(
        lambda tr: vector_admit(tr, meta, tree))
    assert [a.allocation_key for a in v_adm] == \
        [a.allocation_key for a in l_adm], "vector gate diverged from legacy"
    assert v_held == l_held, (v_held, l_held)
    out = {
        "asks": n_asks,
        "legacy_ms": round(legacy_ms, 2),
        "vector_ms": round(vector_ms, 2),
        "speedup": round(legacy_ms / max(vector_ms, 1e-9), 2),
        "held": v_held,
        "passes": stats.get("passes"),
        "rank_ms": round(stats.get("rank_ms", 0.0), 2),
        "admit_ms": round(stats.get("admit_ms", 0.0), 2),
    }
    if device:
        from yunikorn_tpu.ops import gate_solve

        # warm=1: the first call at a bucket pays the XLA compile; the
        # steady-state number is what a production cycle pays
        device_ms, (d_adm, d_held, d_stats) = run(
            lambda tr: gate_solve.device_admit(
                extract_problem(tr, meta, tree)), warm=1)
        assert [a.allocation_key for a in d_adm] == \
            [a.allocation_key for a in l_adm], "device gate diverged"
        assert d_held == l_held, (d_held, l_held)
        out.update({
            "device_ms": round(device_ms, 2),
            "device_speedup": round(legacy_ms / max(device_ms, 1e-9), 2),
            "device_vs_vector": round(vector_ms / max(device_ms, 1e-9), 2),
            "device_passes": d_stats.get("passes"),
            "max_passes": d_stats.get("max_passes",
                                      gate_solve.max_passes_for(n_asks)),
            "device_finish_loop": d_stats.get("finish_loop", 0),
        })
    return out


def _churn_harness(n_pods, churn, n_nodes=64):
    """Shared churn-trace scaffolding for the two O(changed) contracts: an
    encoder over a node cache, a pod/ask batch builder, and the 1%-churn
    mutation (fresh seq + changed request — both contracts must see the
    SAME workload). Returns (enc, asks, mutate, n_changed)."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.update_node(make_node(f"n{i}", cpu_milli=64000,
                                    memory=128 * 2**30))
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = [make_pod(f"p{i}", cpu_milli=100) for i in range(n_pods)]
    asks = [AllocationAsk(p.uid, "app", get_pod_resource(p), pod=p, seq=i)
            for i, p in enumerate(pods)]
    n_changed = max(int(n_pods * churn), 1)

    def mutate():
        for i in range(n_changed):
            p = make_pod(f"p{i}", cpu_milli=700)
            asks[i] = AllocationAsk(asks[i].allocation_key, "app",
                                    get_pod_resource(p), pod=p,
                                    seq=n_pods + i)

    return enc, asks, mutate, n_changed


def churn_check(n_pods=2000, churn=0.01):
    """1%-churn contract: the second encode re-derives only changed rows."""
    enc, asks, mutate, n_changed = _churn_harness(n_pods, churn)
    t0 = time.perf_counter()
    enc.build_batch(asks)
    cold_ms = (time.perf_counter() - t0) * 1000
    mutate()
    t0 = time.perf_counter()
    enc.build_batch(asks)
    churn_ms = (time.perf_counter() - t0) * 1000
    out = {
        "pods": n_pods,
        "changed": n_changed,
        "rows_reencoded": enc.last_encode_rows_reencoded,
        "cold_encode_ms": round(cold_ms, 2),
        "churn_encode_ms": round(churn_ms, 2),
    }
    print(json.dumps(out), flush=True)
    assert enc.last_encode_rows_reencoded == n_changed, \
        (enc.last_encode_rows_reencoded, n_changed)
    return out


def device_churn_check(n_pods=2000, churn=0.01):
    """O(changed) TRANSFER contract: the second sync uploads only the
    changed rows' data into the device row pool."""
    enc, asks, mutate, n_changed = _churn_harness(n_pods, churn)
    store = enc.device_row_store()
    t0 = time.perf_counter()
    store.sync_and_gather(asks, n_pods)
    cold_ms = (time.perf_counter() - t0) * 1000
    mutate()
    t0 = time.perf_counter()
    store.sync_and_gather(asks, n_pods)
    churn_ms = (time.perf_counter() - t0) * 1000
    out = {
        "pods": n_pods,
        "changed": n_changed,
        "rows_uploaded": store.last_upload_rows,
        "bytes_uploaded": store.last_upload_bytes,
        "cold_sync_ms": round(cold_ms, 2),
        "churn_sync_ms": round(churn_ms, 2),
    }
    print(json.dumps(out), flush=True)
    assert store.last_upload_rows == n_changed, \
        (store.last_upload_rows, n_changed)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="2000,20000")
    ap.add_argument("--assert-speedup", type=int, default=0, metavar="N",
                    help="exit 1 unless vector beats legacy at sizes >= N")
    ap.add_argument("--device", action="store_true",
                    help="also run the jitted device scan")
    ap.add_argument("--passes", action="store_true",
                    help="pass report + regression assertion: the device "
                         "scan must finish within its log-depth bound "
                         "(ceil(log2(n))+C) at every size (implies "
                         "--device)")
    ap.add_argument("--churn-check", action="store_true")
    ap.add_argument("--device-churn-check", action="store_true")
    ap.add_argument("--contended", action="store_true",
                    help="quotas at ~80%% of demand (~26%% held): every "
                         "(leaf, user) limit saturated")
    ap.add_argument("--saturated", action="store_true",
                    help="quotas at ~16%% of demand (~85%% held): the "
                         "adversarial multi-pass convergence shape")
    args = ap.parse_args()

    scale = 0.2 if args.saturated else (1.0 if args.contended else 1.3)
    device = args.device or args.passes
    failed = False
    for size in (int(s) for s in args.sizes.split(",") if s):
        r = bench_size(size, scale=scale, device=device)
        print(json.dumps(r), flush=True)
        if args.assert_speedup and size >= args.assert_speedup \
                and r["speedup"] <= 1.0:
            print(f"# FAIL: vector gate did not beat the legacy loop at "
                  f"{size} asks ({r['speedup']}x)", file=sys.stderr)
            failed = True
        if args.passes:
            print(f"# passes @ {size}: host-vector={r['passes']} "
                  f"device={r['device_passes']} "
                  f"bound={r['max_passes']} "
                  f"(leftovers={r['device_finish_loop']})", flush=True)
            if r["device_passes"] > r["max_passes"]:
                print(f"# FAIL: device pass count {r['device_passes']} "
                      f"exceeds the log-depth bound {r['max_passes']} at "
                      f"{size} asks", file=sys.stderr)
                failed = True
    if args.churn_check:
        churn_check()
    if args.device_churn_check:
        device_churn_check()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
