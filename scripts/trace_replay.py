#!/usr/bin/env python
"""Trace-replay proving ground: seeded synthetic fleet traces through the
FULL shim path, SLO-gated.

The kwok-perf-test analog the ROADMAP's top item asks for: instead of a
single-shape microbench, a seeded multi-tenant trace generator pumps pod
waves through the real adapter (client/kube.py reflectors over HTTP) against
`tests/fake_apiserver.py` at up to 10k-100k simulated nodes, while the
streaming SLO engine (obs/slo.py) evaluates rolling-window objectives — p99
pod e2e latency, cycle staleness, degraded-tier dwell, mis-evictions, AOT
cold start. The replay report's pass/fail IS the engine's verdicts: this is
the first PR-gateable artifact beyond microbenches.

Traces (all seeded-deterministic: same seed => identical event list, and an
identical report modulo the `timings` section):

  diurnal        sinusoidal multi-tenant arrival wave with pod completions
                 trailing behind (the million-user daily shape)
  gang-storm     bursts of gang applications landing at once per tenant,
                 drained between storms
  quota-churn    steady arrivals while the quota configmap flips every few
                 seconds (gate/queue-meta recompute under churn)
  drain-upgrade  steady arrivals + a rack of nodes drained mid-trace and
                 rolled back in (node-drain + rolling-upgrade)
  restart-storm  gang storm with a scheduler restart mid-storm: core+shim
                 torn down and rebuilt against the live API server (state
                 recovery under pressure). --restart-mode inprocess (the
                 default) rebuilds inside this interpreter; --restart-mode
                 process spawns a GENUINELY FRESH interpreter that takes
                 over scheduling against the live server for a takeover
                 window — with --aot-store its first admitted cycle is the
                 true process-boundary cold start, measured by the child's
                 own SLO engine against the aot_cold_start budget, and the
                 child verifies recovery restored every bound pod with
                 zero lost bindings and zero mis-evictions (the fresh-
                 process verdict scripts/aot_smoke.py covers for the bare
                 solver, now covered for the full shim path).
  slice-fragmentation
                 mixed-size gangs churning across ICI domains: nodes carry
                 synthesized topology labels (fake_apiserver.topology_labels)
                 and ~60%% of each wave completes before the next lands, so
                 free capacity fragments across domains and late gangs must
                 find contiguous slots. The report fingerprint gains a
                 `topology` block (mode, gangs, cross-domain-gang count,
                 final fragmentation) — the round-15 A/B artifact
                 (--topology false replays the identical trace un-steered).

Chaos coupling (--fault hang|fail): a scripted robustness/faults.py fault
poisons the supervised assign path mid-trace — the staleness objective must
detect it (`--expect-violation` asserts that it does).

Shard failover (--kill-shard N, needs --shards >= 2): kills ONE shard's
scheduling loop mid-trace (--kill-mode crash = faults.crash unwinds the
loop thread; wedge = a slow fault past every deadline). The failure-domain
supervisor (robustness/failover.py) must detect it, QUARANTINE the shard,
re-home 100%% of its node domains onto survivors and re-admit its parked
asks — `--assert-failover` gates on exactly that (plus a clean ledger
audit and every pod bound).

A/B (--ab): replays the identical trace under solver.policy=greedy and
=optimal — and, when --policy-checkpoint names a trained learned-policy
checkpoint, a THIRD arm under solver.policy=learned — recording preemption
volume + placement counts for every arm, with the policy (and the active
checkpoint hash) named in each arm's fingerprint block so A/B reports stay
seed-reproducible across checkpoints. --assert-quality gates the learned
arm against the greedy arm (never fewer pods bound). --dataset-out records
every choose_plan duel the replay's core runs (raw solve tensors + plans +
winner) as a training dataset for scripts/policy_train.py — the scheduler
feeding its own training loop.

Usage (acceptance shape):
    python scripts/trace_replay.py --trace gang-storm --nodes 10000 --assert-slo
    python scripts/trace_replay.py --trace gang-storm --fault hang --expect-violation
Exit 0 = asserted condition holds; nonzero names the objective(s).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import ssl
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACES = ("diurnal", "gang-storm", "quota-churn", "drain-upgrade",
          "restart-storm", "slice-fragmentation")


# ---------------------------------------------------------------------------
# Trace generation (pure + seeded: importable by tests for determinism)
# ---------------------------------------------------------------------------
def _queues_yaml(tenants: List[str], max_vcore: int = 0) -> str:
    lines = ["partitions:", "  - name: default", "    queues:",
             "      - name: root", "        queues:"]
    for t in tenants:
        lines.append(f"          - name: {t}")
        if max_vcore:
            lines.append("            resources:")
            lines.append(f"              max: {{vcore: {max_vcore}, "
                         f"memory: {max_vcore * 4}Gi}}")
    return "\n".join(lines) + "\n"


def generate_trace(trace: str, *, seed: int, nodes: int, pods: int,
                   tenants: int, duration: float,
                   overcommit: float = 1.0,
                   quota_max_vcore: int = 0) -> Tuple[List[tuple], dict]:
    """Build the deterministic event list for one replay.

    Returns (events, meta): events is a time-sorted list of
    (t_offset_s, kind, payload) tuples — kinds: "pods" (list of
    (name, app, queue, cpu_m, mem_mi, priority)), "complete" (int n oldest
    bound pods marked Succeeded), "drain"/"add_nodes" (node-name lists),
    "configmap" (flattened data dict), "restart" (scheduler rebuild).
    meta carries max_wave (peak concurrent arrivals, sizes the warm-up
    bucket), the tenant list and the queues.yaml the replay boots with.
    Purely a function of its arguments — the seeded-determinism contract
    the replay report's fingerprint is checked against.
    """
    if trace not in TRACES:
        raise ValueError(f"unknown trace {trace!r} (have {TRACES})")
    rng = random.Random(seed)
    tnames = [f"t{i}" for i in range(max(1, tenants))]
    events: List[tuple] = []
    counter = [0]

    def mk_pods(n: int, t: float, prio_of=None, app_of=None,
                tenant_of=None) -> int:
        batch = []
        for _ in range(n):
            i = counter[0]
            counter[0] += 1
            tn = tenant_of(i) if tenant_of else tnames[i % len(tnames)]
            app = app_of(i, tn) if app_of else f"rapp-{tn}"
            prio = prio_of(i) if prio_of else 0
            batch.append((f"rp-{i}", app, f"root.{tn}", 100, 64, prio))
        if batch:
            events.append((t, "pods", batch))
        return len(batch)

    max_wave = 0
    if trace in ("gang-storm", "restart-storm"):
        storms = 3
        per_storm = max(pods // storms, 1)
        gang = max(4, min(32, per_storm // (4 * len(tnames)) or 4))
        for s in range(storms):
            t_s = duration * (s + 0.15) / storms
            left = per_storm
            g_i = 0
            while left > 0:
                n = min(gang, left)
                left -= n
                jitter = rng.random() * min(2.0, duration / 15)
                mk_pods(n, t_s + jitter,
                        app_of=lambda i, tn, s=s, g=g_i: f"gang-{s}-{g}-{tn}")
                g_i += 1
            max_wave = max(max_wave, per_storm)
            # drain half the storm before the next one lands
            events.append((t_s + duration / storms * 0.6, "complete",
                           per_storm // 2))
        if trace == "restart-storm":
            events.append((duration * 0.5, "restart", None))
    elif trace == "diurnal":
        steps = max(8, min(60, int(duration)))
        dt = duration / steps
        weights = [1.0 + math.sin(2 * math.pi * k / steps - math.pi / 2)
                   for k in range(steps)]
        wsum = sum(weights) or 1.0
        arrivals = [int(round(pods * w / wsum)) for w in weights]
        lifetime_steps = max(2, steps // 3)
        for k, n in enumerate(arrivals):
            if n:
                mk_pods(n, k * dt)
                max_wave = max(max_wave, n)
            done_k = k - lifetime_steps
            if done_k >= 0 and arrivals[done_k]:
                events.append((k * dt + dt / 2, "complete",
                               arrivals[done_k]))
    elif trace == "quota-churn":
        steps = max(6, min(40, int(duration / 1.5)))
        dt = duration / steps
        per = max(pods // steps, 1)
        for k in range(steps):
            mk_pods(per, k * dt)
            max_wave = max(max_wave, per)
        churn_every = max(2.0, duration / 8)
        t = churn_every
        flip = False
        while t < duration:
            # flip between unbounded and a generous max: the gate's
            # queue-meta/tracker state rebuilds every flip, admission stays
            # unconstrained (the churn, not starvation, is the workload)
            data = {"queues.yaml": _queues_yaml(
                tnames, max_vcore=0 if flip else 10_000_000)}
            events.append((t, "configmap", data))
            flip = not flip
            t += churn_every
    elif trace == "slice-fragmentation":
        # mixed gang sizes churning: waves of gangs sized 2/3/5/8 land per
        # tenant; most of each wave completes before the next arrives, so
        # the free capacity the next wave sees is scattered across ICI
        # domains — exactly the fragmentation the topology-aware score must
        # defragment (gangs into one domain) instead of amplifying
        waves = 4
        per_wave = max(pods // waves, 1)
        sizes = (2, 3, 5, 8)
        for w in range(waves):
            t_w = duration * (w + 0.12) / waves
            left = per_wave
            g_i = 0
            while left > 0:
                n = min(sizes[(g_i + w) % len(sizes)], left)
                left -= n
                jitter = rng.random() * min(1.5, duration / 20)
                # one tenant per GANG (not the per-pod round-robin): a gang
                # is one application, and an application lives in one queue
                # — the per-pod tenant stripe would shatter every gang into
                # singleton apps and empty the contiguity denominator
                tn_g = tnames[(g_i + w) % len(tnames)]
                mk_pods(n, t_w + jitter,
                        app_of=lambda i, tn, w=w, g=g_i: f"frag-{w}-{g}-{tn}",
                        tenant_of=lambda i, tn=tn_g: tn)
                g_i += 1
            max_wave = max(max_wave, per_wave)
            events.append((t_w + duration / waves * 0.55, "complete",
                           int(per_wave * 0.6)))
    elif trace == "drain-upgrade":
        steps = max(6, min(40, int(duration)))
        dt = duration / steps
        per = max(pods // steps, 1)
        for k in range(steps):
            mk_pods(per, k * dt)
            max_wave = max(max_wave, per)
        rack = [f"rn-{i}" for i in range(max(1, min(nodes // 50, 64)))]
        events.append((duration * 0.3, "drain", rack))
        # rolling re-add in two chunks (the upgrade's second half)
        half = max(1, len(rack) // 2)
        events.append((duration * 0.65, "add_nodes", rack[:half]))
        events.append((duration * 0.8, "add_nodes", rack[half:]))

    events.sort(key=lambda e: (e[0], e[1]))
    meta = {
        "tenants": tnames,
        # a nonzero quota max creates one ledger tracker per tenant queue:
        # every pod then rides reserve/confirm/release through the quota
        # plane — the ledger chaos drills need that traffic on the wire
        "queues_yaml": _queues_yaml(tnames, max_vcore=quota_max_vcore),
        "max_wave": max_wave,
        "pods_total": counter[0],
        "overcommit": overcommit,
    }
    return events, meta


# ---------------------------------------------------------------------------
# Replay stack: real adapter + core + shim over the fake API server
# ---------------------------------------------------------------------------
def _pod_doc(name: str, app: str, queue: str, cpu_m: int, mem_mi: int,
             priority: int) -> dict:
    doc = {
        "metadata": {"name": name, "namespace": "default",
                     "labels": {"applicationId": app, "queue": queue},
                     "creationTimestamp": "2026-01-01T00:00:00Z"},
        "spec": {"schedulerName": "yunikorn",
                 "containers": [{"name": "main", "resources": {"requests": {
                     "cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"}}}]},
        "status": {"phase": "Pending"},
    }
    if priority:
        doc["spec"]["priority"] = priority
    return doc


class ReplayStack:
    """Owns the scheduler side (provider/cache/core/shim) over a shared
    FakeAPIServer; restart() rebuilds it in place — the restart-storm
    trace's recovery-under-pressure seam. server may be None when the
    stack is a fresh-process takeover child attaching to a live server it
    does not own."""

    def __init__(self, server, port: int, conf_map: Dict[str, str],
                 policy: str, recorder=None, ledger_serve: bool = False):
        self.server = server
        self.port = port
        self.conf_map = dict(conf_map)
        self.policy = policy
        # --ledger-socket: the quota authority serves behind a local
        # socket and every shard couples through LedgerClient (the RPC
        # boundary the netsplit/ledger-lag faults and the host-kill
        # lease drill act on)
        self.ledger_serve = bool(ledger_serve)
        # policy duel recorder (policy/train.DatasetWriter): re-attached on
        # every (re)boot so a restart-storm rebuild keeps recording
        self.recorder = recorder
        self.violations_history: List[Dict[str, int]] = []
        # counters that must SURVIVE a restart: the rebuilt core's metrics
        # start at zero, and a report reading only the final core would
        # silently LOSE every pre-restart preemption and mis-eviction —
        # the mis-eviction ledger across restart would under-count
        self._counters_history: List[Dict[str, int]] = []
        self.takeover_reports: List[dict] = []
        self.restarts = 0
        self.restart_first_cycle_ms: Optional[float] = None
        self.core = self.shim = self.provider = None
        self._boot()

    def _boot(self) -> None:
        from yunikorn_tpu.cache.context import Context
        from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
        from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider
        from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
        from yunikorn_tpu.core.scheduler import SolverOptions
        from yunikorn_tpu.core.shard import make_core_scheduler
        from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
        from yunikorn_tpu.obs.flightrec import FlightRecorderOptions
        from yunikorn_tpu.obs.slo import SloOptions
        from yunikorn_tpu.robustness.failover import FailoverOptions
        from yunikorn_tpu.robustness.supervisor import SupervisorOptions
        from yunikorn_tpu.shim.scheduler import KubernetesShim

        reset_for_tests()
        holder = get_holder()
        holder.update_config_maps([self.conf_map], initial=True)
        dispatch_mod.reset_dispatcher()
        cfg = KubeConfig(f"http://127.0.0.1:{self.port}",
                         ssl.create_default_context())
        self.provider = RealAPIProvider(cfg)
        cache = SchedulerCache()
        conf = holder.get()
        ledger_kw = {}
        if self.ledger_serve:
            from yunikorn_tpu.core.ledger_service import LedgerClientOptions

            ledger_kw = {"ledger_serve": True,
                         "ledger_client_options":
                             LedgerClientOptions.from_conf(conf)}
        self.core = make_core_scheduler(
            cache, shards=conf.solver_shards, interval=conf.interval,
            solver_options=SolverOptions.from_conf(conf),
            supervisor_options=SupervisorOptions.from_conf(conf),
            slo_options=SloOptions.from_conf(conf),
            failover_options=FailoverOptions.from_conf(conf),
            journey_capacity=conf.obs_journey_capacity,
            flightrec_options=FlightRecorderOptions.from_conf(conf),
            **ledger_kw)
        if self.recorder is not None:
            target = getattr(self.core, "primary", self.core)
            if hasattr(target, "policy_recorder"):
                target.policy_recorder = self.recorder
        ctx = Context(self.provider, self.core, cache=cache)
        self.shim = KubernetesShim(self.provider, self.core, context=ctx)
        self.core.start()
        self.shim.run()

    def stop(self) -> None:
        if self.core is not None:
            self.core.stop()
        if self.shim is not None:
            self.shim.stop()
        if self.provider is not None:
            self.provider.stop()

    def _counter_snapshot(self) -> Dict[str, int]:
        return {
            "preempted_total": int(
                self.core.obs.get("preempted_total").value()),
            "mis_evictions": int(self.core.obs.get(
                "preemption_mis_evictions_total").value()),
        }

    def restart(self, takeover: Optional[dict] = None) -> None:
        """Scheduler-pod restart against the live API server: verdicts,
        violation and preemption/mis-eviction counts recorded so far are
        carried into the report's history (a rebuilt core's counters start
        at zero — dropping them would make the mis-eviction ledger lose
        residue across restarts); the fresh core recovers bound pods +
        pending asks from the server's state.

        takeover != None runs the TRUE fresh-process restart first: a new
        interpreter (child_takeover) schedules against the live server for
        the takeover window, measures the process-boundary cold start and
        verifies recovery, then exits; this stack reboots in-process to
        finish the trace (a second recovery)."""
        self.violations_history.append(self.core.slo.violations())
        self._counters_history.append(self._counter_snapshot())
        self.stop()
        self.restarts += 1
        if takeover is not None:
            rep = self._run_takeover(takeover)
            self.takeover_reports.append(rep)
            self.restarts += 1  # the child's boot is a restart too
            self.violations_history.append(rep.get("violations") or {})
            self._counters_history.append({
                "preempted_total": int(rep.get("preempted_total", 0)),
                "mis_evictions": int(rep.get("mis_evictions", 0)),
            })
        self._boot()
        # the rebuilt core's first admitted cycle is the restart's measured
        # cold start (an attached AOT store serves it from artifacts)
        t0 = time.time()
        while time.time() - t0 < 120:
            if self.core._first_cycle_ms is not None:
                self.restart_first_cycle_ms = self.core._first_cycle_ms
                break
            time.sleep(0.2)

    def _run_takeover(self, spec: dict) -> dict:
        """Spawn the fresh-interpreter takeover child against the live
        server and collect its one-line JSON report."""
        import subprocess
        import tempfile

        fd, conf_path = tempfile.mkstemp(suffix=".json",
                                         prefix="yk-takeover-")
        with os.fdopen(fd, "w") as f:
            json.dump(self.conf_map, f)
        cmd = [sys.executable, os.path.abspath(__file__), "--takeover",
               "--takeover-port", str(self.port),
               "--takeover-conf", conf_path,
               "--takeover-window", str(spec.get("window", 25.0))]
        if spec.get("aot_store"):
            cmd += ["--aot-store", spec["aot_store"]]
        env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
        env.setdefault("JAX_PLATFORMS", "cpu")
        print(f"[replay] spawning fresh-process takeover: {' '.join(cmd)}",
              file=sys.stderr, flush=True)
        try:
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=float(spec.get("timeout", 600.0)),
                                   env=env)
            except subprocess.TimeoutExpired as e:
                # surface whatever the wedged child printed, and fail the
                # structured way (the smoke greps the [replay] FAIL shape)
                sys.stderr.write((e.stdout or b"")[-4000:].decode(
                    "utf-8", "replace") if isinstance(e.stdout, bytes)
                    else (e.stdout or "")[-4000:])
                raise RuntimeError(
                    f"fresh-process takeover timed out after {e.timeout}s"
                ) from e
            line = next((ln for ln in reversed(r.stdout.splitlines())
                         if ln.startswith("TAKEOVER_REPORT ")), None)
            if r.returncode != 0 or line is None:
                sys.stderr.write(r.stdout[-4000:])
                sys.stderr.write(r.stderr[-4000:])
                raise RuntimeError(
                    f"fresh-process takeover failed rc={r.returncode}")
            rep = json.loads(line[len("TAKEOVER_REPORT "):])
        finally:
            try:
                os.unlink(conf_path)
            except OSError:
                pass
        print(f"[replay] takeover done: cold={rep.get('first_cycle_ms')}ms "
              f"({rep.get('cold_verdict')}), restored="
              f"{rep.get('restored_allocations')}/"
              f"{rep.get('bound_at_boot')}, lost={rep.get('lost_bound')}, "
              f"mis_evictions={rep.get('mis_evictions')}",
              file=sys.stderr, flush=True)
        return rep

    def merged_violations(self) -> Dict[str, int]:
        out = self.core.slo.violations()
        for past in self.violations_history:
            for k, v in past.items():
                out[k] = out.get(k, 0) + v
        return out

    def merged_counter(self, name: str) -> int:
        cur = self._counter_snapshot()[name]
        return cur + sum(past.get(name, 0)
                         for past in self._counters_history)


# ---------------------------------------------------------------------------
# Fresh-process takeover child (--takeover; internal)
# ---------------------------------------------------------------------------
def _count_restored_allocations(core, uids=None) -> int:
    """Non-placeholder allocations registered across every shard's
    partitions — recovery restores one per bound pod. With `uids`, count
    ONLY allocations whose key is in that set (allocation keys are pod
    uids): the takeover child passes the uids of pods bound at BOOT, so
    its own post-recovery bindings can never inflate the restored count."""
    total = 0
    for c in getattr(core, "shards", None) or [core]:
        with c._lock:
            for part in c.partitions.values():
                for app in part.applications.values():
                    total += sum(1 for k, a in app.allocations.items()
                                 if not a.placeholder
                                 and (uids is None or k in uids))
    return total


def child_takeover(args) -> int:
    """A GENUINELY fresh interpreter booted mid-restart-storm: attach to
    the live fake API server, recover its state through the real adapter,
    serve the storm for the takeover window, and report the process-
    boundary cold start + recovery verdict as one JSON line.

    This is the restart the in-process rebuild cannot represent: jit
    caches, interned vocabularies, device buffers and the AOT runtime all
    start empty here — with --aot-store the first admitted cycle is
    artifact-load + execute, without one it is the full XLA compile stall,
    and the child's own SLO engine scores it against the aot_cold_start
    budget carried in the conf map."""
    import urllib.request

    from yunikorn_tpu.utils.jaxtools import (ensure_compilation_cache,
                                             force_cpu_platform)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        force_cpu_platform(int(os.environ.get("YK_REPLAY_CPU_DEVICES", "1")))
    ensure_compilation_cache()
    rt = None
    if args.aot_store:
        from yunikorn_tpu import aot

        rt = aot.install(args.aot_store, background=False)
    with open(args.takeover_conf) as f:
        conf_map = json.load(f)
    port = args.takeover_port

    def bound_pods() -> Dict[str, dict]:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/pods", timeout=10) as r:
            docs = json.loads(r.read()).get("items", [])
        # completed pods keep their nodeName but hold no allocation — the
        # recovery contract covers LIVE bound pods only
        return {d["metadata"]["name"]: {"node": d["spec"]["nodeName"],
                                        "uid": d["metadata"].get("uid", "")}
                for d in docs
                if d.get("spec", {}).get("nodeName")
                and d.get("status", {}).get("phase")
                not in ("Succeeded", "Failed")}

    pre = bound_pods()
    pre_uids = {v["uid"] for v in pre.values() if v["uid"]}
    t0 = time.time()
    stack = ReplayStack(None, port, conf_map, "takeover")
    out: dict = {"bound_at_boot": len(pre)}
    try:
        deadline = t0 + args.takeover_window
        while time.time() < deadline:
            stack.core.slo.maybe_tick()
            # once the cold start is measured, half a window of serving is
            # enough evidence — the parent resumes the storm afterwards
            if (stack.core._first_cycle_ms is not None
                    and time.time() - t0 >= args.takeover_window / 2):
                break
            time.sleep(0.2)
        post = bound_pods()
        lost = sorted(
            n for n, v in pre.items()
            if (post.get(n) or {}).get("node") != v["node"])
        stack.core.slo.tick()
        slo_report = stack.core.slo.report()
        cold = slo_report["objectives"]["aot_cold_start"]
        out.update({
            "first_cycle_ms": stack.core._first_cycle_ms,
            "cold_verdict": cold["verdict"],
            "cold_budget_ms": cold["target"],
            # keyed by the BOOT-time bound pods' uids: the child's own new
            # bindings cannot inflate the restored count
            "restored_allocations": _count_restored_allocations(
                stack.core, uids=pre_uids),
            "lost_bound": len(lost),
            "lost_names": lost[:8],
            "mis_evictions": int(stack.core.obs.get(
                "preemption_mis_evictions_total").value()),
            "preempted_total": int(
                stack.core.obs.get("preempted_total").value()),
            "violations": stack.core.slo.violations(),
            "bound_at_exit": len(post),
            "window_s": round(time.time() - t0, 2),
            "aot_hits": rt.stats()["hits"] if rt is not None else 0,
        })
    finally:
        stack.stop()
    print("TAKEOVER_REPORT " + json.dumps(out), flush=True)
    return 0


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------
def _complete_bound(server, ledger: dict, n: int) -> int:
    """Mark the n oldest still-running replay pods Succeeded (the kubelet
    finishing work): frees capacity and exercises release accounting."""
    done = 0
    for name, _node in list(server.bindings):
        if done >= n:
            break
        if not name.startswith(("rp-", "warm-")) or name in ledger["completed"]:
            continue
        with server._lock:
            doc = server.store["pods"].get(f"default/{name}")
        if doc is None:
            continue
        doc = json.loads(json.dumps(doc))
        doc.setdefault("status", {})["phase"] = "Succeeded"
        server.add("pods", doc)
        ledger["completed"].add(name)
        done += 1
    return done


def run_replay(args, policy: str) -> dict:
    from tests.fake_apiserver import FakeAPIServer
    from yunikorn_tpu.utils.jaxtools import (ensure_compilation_cache,
                                             force_cpu_platform)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        force_cpu_platform(int(os.environ.get("YK_REPLAY_CPU_DEVICES", "1")))
    # the bucket prewarm populates the PERSISTENT compile cache (compile_only
    # never grows the in-process jit caches) — without this the production
    # dispatch re-pays the full XLA compile inside the measured window
    ensure_compilation_cache()

    events, meta = generate_trace(
        args.trace, seed=args.seed, nodes=args.nodes, pods=args.pods,
        tenants=args.tenants, duration=args.duration,
        overcommit=args.overcommit,
        quota_max_vcore=getattr(args, "quota_max_vcore", 0))

    t_run0 = time.time()
    server = FakeAPIServer()
    port = server.start()
    with_topology = (args.trace == "slice-fragmentation"
                     or args.topology_labels)
    # ICI domain per node, recorded at ADD time: the contiguity ground
    # truth must survive node deletion (drain/upgrade traces) — reading
    # the final store would count a gang on since-drained nodes as
    # cross-domain
    dom_of_node: Dict[str, str] = {}

    def _add_node(name: str, idx: int) -> None:
        server.add_node_doc(name, cpu="8", memory="16Gi",
                            topology_index=idx if with_topology else None,
                            nodes_per_domain=args.nodes_per_domain)
        if with_topology:
            from yunikorn_tpu.topology.model import (LABEL_ICI_DOMAIN,
                                                     LABEL_SLICE)

            lbl = FakeAPIServer.topology_labels(
                idx, nodes_per_domain=args.nodes_per_domain)
            dom_of_node[name] = (f"{lbl[LABEL_SLICE]}/"
                                 f"{lbl[LABEL_ICI_DOMAIN]}")

    for i in range(args.nodes):
        _add_node(f"rn-{i}", i)
    print(f"[replay] fake apiserver on :{port} with {args.nodes} nodes "
          f"({args.trace}, seed={args.seed}, policy={policy})",
          file=sys.stderr, flush=True)

    fast_w = max(5.0, args.duration / 4)
    slow_w = args.duration * 2 + 60
    conf_map = {
        "service.schedulingInterval": str(args.interval),
        "queues.yaml": meta["queues_yaml"],
        "log.level": "WARN",
        "observability.sloFastWindowSeconds": str(fast_w),
        "observability.sloSlowWindowSeconds": str(slow_w),
        "observability.sloPodE2eP99Seconds": str(args.slo_e2e),
        "observability.sloCycleStalenessSeconds": str(args.slo_staleness),
        "observability.sloColdStartBudgetMs": str(args.slo_cold_budget_ms),
        # fault traces degrade by design; dwell stays informational here
        "observability.sloDegradedDwellBudget": "0.9",
        "solver.policy": policy,
        # generous enough for a warm full-bucket dispatch at the replay's
        # node scale (a 10k-node solve is seconds on a loaded CPU box), yet
        # small enough that the scripted hang trips it inside the window;
        # recovery probes must reclaim tiers before the drain ends
        "robustness.dispatchDeadlineSeconds": str(args.dispatch_deadline),
        "robustness.maxRetries": "0",
        "robustness.breakerThreshold": "2",
        "robustness.probeIntervalSeconds": "1",
        "solver.topology": args.topology,
        # control-plane sharding (core/shard.py): N pipelined shards over
        # disjoint topology-aligned node partitions behind one front end
        "solver.shards": str(args.shards),
        # shard failover (robustness/failover.py): the kill-shard dial
        # compresses these to seconds so detection + re-home land inside
        # the trace window
        "robustness.failoverStaleSeconds": str(args.failover_stale),
        "robustness.failoverProbeSeconds": str(args.failover_probe),
        "robustness.failoverRejoinSeconds": str(args.failover_rejoin),
    }
    if args.ledger_socket:
        # ledger-as-a-service (round 22): the lease TTL is compressed so
        # the --kill-mode lease drill detects the dead peer inside the
        # trace window; fail-closed flips degraded-mode admission from
        # conservative-local to reject-everything
        conf_map["robustness.ledgerLeaseTtlSeconds"] = str(args.lease_ttl)
        conf_map["robustness.ledgerFailClosed"] = (
            "true" if args.ledger_fail_closed else "false")
    if args.flightrec_dir:
        # triggered flight recorder (round 20): SLO violations, shard
        # quarantines, breaker exhaustion and watchdog abandonment each
        # dump a bounded post-mortem bundle into this dir mid-replay. The
        # debounce outlives the run: one bundle per trigger per replay
        # (the first edge is the evidence; repeats within a run are the
        # same incident)
        conf_map["observability.flightRecorderDir"] = args.flightrec_dir
        conf_map["observability.flightRecorderDebounceSeconds"] = str(
            args.duration * 2 + args.drain_timeout + 600)
    if args.policy_checkpoint:
        # learned-policy checkpoint (round 17): only the learned arm
        # dispatches it, but the conf rides every arm so the A/B replays
        # one identical configuration modulo solver.policy
        conf_map["solver.policyCheckpoint"] = args.policy_checkpoint
    if args.aot_store:
        from yunikorn_tpu import aot

        aot.install(args.aot_store, background=False)

    recorder = None
    if args.dataset_out:
        from yunikorn_tpu.policy.train import DatasetWriter

        if args.shards > 1:
            print("[replay] WARNING: --dataset-out records the primary "
                  "shard only", file=sys.stderr, flush=True)
        runs_duels = (policy in ("optimal", "all")
                      or (policy == "learned" and args.policy_checkpoint))
        if not runs_duels:
            # greedy never duels; learned without a checkpoint skips every
            # cycle ("no-checkpoint") — either way the dataset stays empty
            print(f"[replay] WARNING: --dataset-out records choose_plan "
                  f"duels, and solver.policy={policy} runs none here "
                  "(use optimal/all, or learned WITH --policy-checkpoint)",
                  file=sys.stderr, flush=True)
        # each --ab arm records into its own subdirectory: DatasetWriter
        # owns (and wipes) its dir, so arms sharing one path would erase
        # each other's cycles
        ds_path = (os.path.join(args.dataset_out, policy) if args.ab
                   else args.dataset_out)
        recorder = DatasetWriter(ds_path,
                                 max_cycles=args.dataset_max_cycles)

    stack = ReplayStack(server, port, conf_map, policy, recorder=recorder,
                        ledger_serve=args.ledger_socket)
    ledger = {"completed": set()}
    timings: Dict[str, object] = {}
    try:
        # ---- warm-up: compile/load every bucket the storm will hit, then
        # wipe the SLO windows so the measured phase starts clean ----
        # Bucket prewarm first (the production deployment's --prewarm):
        # trace waves land at arbitrary bucket sizes, and a 10k-node-wide
        # compile mid-storm is tens of seconds on CPU — enough to trip the
        # dispatch deadline and fail the staleness objective for reasons
        # that are about THIS box's compiler, not the scheduler.
        t0 = time.time()
        warm_n = max(32, min(meta["max_wave"], 4096))
        if not args.no_prewarm:
            from yunikorn_tpu.utils.jaxtools import prewarm_buckets

            # cap at the trace's TOTAL pods, not its peak wave: overlapping
            # waves accumulate pending asks across cycle boundaries, and an
            # unprewarmed next-bucket compile mid-storm is minutes at 10k
            # nodes — the exact stall the measured window must not contain
            cap = 1 << max(meta["pods_total"] - 1, 31).bit_length()
            buckets, b = [], 32
            while b <= cap:
                buckets.append(b)
                b *= 2
            warm_nodes = [args.nodes]
            if args.shards > 1:
                # each shard solves over its own partition: warm the
                # per-shard node scale too, or every shard's first wave
                # pays a fresh compile at a bucket the fleet-size warm
                # never touched
                warm_nodes.append(max(1, args.nodes // args.shards))
            spec = ",".join(f"{m}x{n}" for m in warm_nodes for n in buckets)
            print(f"[replay] prewarming buckets {spec}", file=sys.stderr,
                  flush=True)
            t = prewarm_buckets(spec,
                                core=getattr(stack.core, "primary",
                                             stack.core))
            t.join(timeout=args.warmup_timeout)
            if t.is_alive():
                print("[replay] WARNING: bucket prewarm still running; "
                      "continuing unwarmed", file=sys.stderr, flush=True)
        for i in range(warm_n):
            tn = meta["tenants"][i % len(meta["tenants"])]
            server.add("pods", _pod_doc(f"warm-{i}", f"warm-{tn}",
                                        f"root.{tn}", 100, 64, 0))
        deadline = time.time() + args.warmup_timeout
        while time.time() < deadline:
            if len({n for n, _ in server.bindings}) >= warm_n:
                break
            time.sleep(0.2)
        warm_bound = len({n for n, _ in server.bindings})
        if warm_bound < warm_n:
            print(f"[replay] WARNING: warm-up bound {warm_bound}/{warm_n} "
                  f"inside {args.warmup_timeout:.0f}s", file=sys.stderr,
                  flush=True)
        _complete_bound(server, ledger, warm_n)
        # warm-up compiles can legitimately trip the dispatch deadline on a
        # loaded box; wait for the half-open probes to reclaim every tier
        # so the measured window starts from a healthy ladder (and say so
        # loudly when they don't — the run is then measuring a degraded
        # scheduler, and the dwell objective will tell)
        deadline = time.time() + max(120.0, 6 * args.dispatch_deadline)
        while (time.time() < deadline
               and stack.core.supervisor.degraded_paths()):
            time.sleep(0.25)
        still = stack.core.supervisor.degraded_paths()
        if still:
            print(f"[replay] WARNING: paths still degraded after warm-up: "
                  f"{still}", file=sys.stderr, flush=True)
        time.sleep(3 * args.interval)
        timings["warmup_s"] = round(time.time() - t0, 2)
        timings["cold_first_cycle_ms"] = stack.core._first_cycle_ms
        stack.core.slo.reset()

        # ---- fault plan (orthogonal to the trace) ----
        run_events = list(events)
        if args.fault != "none":
            t_set = args.duration * 0.35
            t_clear = t_set + max(1.6 * args.slo_staleness,
                                  args.duration * 0.35)
            run_events += [(t_set, "fault_set", args.fault),
                           (t_clear, "fault_clear", None)]
            run_events.sort(key=lambda e: (e[0], e[1]))
        if args.kill_shard >= 0:
            if args.shards < 2:
                raise SystemExit("--kill-shard needs --shards >= 2")
            run_events.append((args.duration * 0.42, "kill_shard",
                               args.kill_shard))
            run_events.sort(key=lambda e: (e[0], e[1]))
        if args.restart_mode == "process":
            # the parent is blocked while the fresh interpreter serves, so
            # pod waves that would land during the takeover window arrive
            # at the restart instant instead — pods arriving while the
            # scheduler is down IS the outage shape, and they form the
            # recovery backlog whose first admitted cycle the child's
            # aot_cold_start verdict measures ("pods" sorts before
            # "restart" at equal t, so they are Pending when it dies)
            t_restart = next((t for t, k, _p in run_events
                              if k == "restart"), None)
            if t_restart is not None:
                horizon = t_restart + args.takeover_window
                run_events = [
                    ((t_restart, k, p)
                     if k == "pods" and t_restart < t <= horizon
                     else (t, k, p))
                    for t, k, p in run_events]
                run_events.sort(key=lambda e: (e[0], e[1]))

        def wait_until(target: float) -> None:
            """Sleep in slices, ticking the SLO engine each slice: the
            driver is the deployment's scrape analog — during a hang the
            run loop is blocked inside the wedged cycle and would never
            tick exactly when the staleness objective must be observed."""
            while True:
                delay = target - time.time()
                if delay <= 0:
                    return
                time.sleep(min(delay, 0.5))
                stack.core.slo.maybe_tick()

        # ---- pump the trace ----
        t_trace0 = time.time()
        created = 0
        for t_off, kind, payload in run_events:
            wait_until(t_trace0 + t_off)
            if kind == "pods":
                for (name, app, queue, cpu_m, mem_mi, prio) in payload:
                    server.add("pods", _pod_doc(
                        name, app, queue,
                        int(cpu_m * max(args.overcommit, 1e-6)), mem_mi,
                        prio))
                    created += 1
            elif kind == "complete":
                _complete_bound(server, ledger, int(payload))
            elif kind == "drain":
                for name in payload:
                    server.delete("nodes", "", name)
            elif kind == "add_nodes":
                for name in payload:
                    _add_node(name, int(name.rsplit("-", 1)[-1]))
            elif kind == "configmap":
                server.add("configmaps", {
                    "metadata": {"name": "yunikorn-configs",
                                 "namespace": "yunikorn"},
                    "data": dict(payload)})
            elif kind == "restart":
                if args.restart_mode == "process":
                    print("[replay] scheduler restart mid-storm "
                          "(fresh-process takeover)", file=sys.stderr,
                          flush=True)
                    stack.restart(takeover={
                        "window": args.takeover_window,
                        "aot_store": args.aot_store,
                        "timeout": max(600.0, 4 * args.takeover_window)})
                else:
                    print("[replay] scheduler restart mid-storm",
                          file=sys.stderr, flush=True)
                    stack.restart()
            elif kind == "kill_shard":
                idx = int(payload)
                print(f"[replay] killing shard {idx} mid-storm "
                      f"({args.kill_mode})", file=sys.stderr, flush=True)
                if args.kill_mode == "lease":
                    # host-kill drill: a peer host registers ownership of
                    # this shard on the ledger liveness authority and then
                    # never heartbeats — its lease expires after the
                    # compressed TTL and the HostLeaseMonitor drives the
                    # shard through quarantine/re-home exactly as if the
                    # owning HOST had died
                    stack.core.ledger.register_host_shards(
                        f"peer-{idx}", [idx])
                elif args.kill_mode == "crash":
                    # the next assign dispatch unwinds the loop thread
                    stack.core.shards[idx].supervisor.faults.crash("assign")
                else:
                    stack.core.shards[idx].supervisor.faults.slow(
                        "assign", seconds=3.0 * args.dispatch_deadline,
                        times=100_000)
            elif kind == "fault_set":
                if payload in ("netsplit", "ledger-lag"):
                    nf = stack.core.ledger.netfaults
                    if payload == "netsplit":
                        print("[replay] partitioning the ledger transport "
                              "(netsplit): breaker opens, degraded-mode "
                              "admission takes over", file=sys.stderr,
                              flush=True)
                        nf.partition()
                    else:
                        print("[replay] injecting 150ms per-frame ledger "
                              "lag", file=sys.stderr, flush=True)
                        nf.delay(0.15)
                    continue
                print(f"[replay] injecting fault {payload!r} on the assign "
                      f"path", file=sys.stderr, flush=True)
                if payload == "hang":
                    # every tier of every dispatch sleeps past the dispatch
                    # deadline: the wedged-XLA shape, via the fault plane
                    stack.core.supervisor.faults.slow(
                        "assign", seconds=3.0 * args.dispatch_deadline,
                        times=10_000)
                else:
                    stack.core.supervisor.faults.fail_forever("assign")
            elif kind == "fault_clear":
                if args.fault in ("netsplit", "ledger-lag"):
                    print("[replay] healing the ledger transport (journal "
                          "replay reconverges the authority)",
                          file=sys.stderr, flush=True)
                    stack.core.ledger.netfaults.heal()
                else:
                    print("[replay] clearing injected fault",
                          file=sys.stderr, flush=True)
                    stack.core.supervisor.faults.clear()
        timings["trace_s"] = round(time.time() - t_trace0, 2)

        # ---- drain: everything created must bind (even across the fault
        # window — recovery is part of the objective) ----
        t_drain0 = time.time()
        want = {f"rp-{i}" for i in range(created)}
        drain_deadline = time.time() + args.drain_timeout
        bound: set = set()
        while time.time() < drain_deadline:
            bound = {n for n, _ in server.bindings if n.startswith("rp-")}
            if want <= bound:
                break
            time.sleep(0.25)
            stack.core.slo.maybe_tick()
        timings["drain_s"] = round(time.time() - t_drain0, 2)
        # settle one fast window so post-recovery verdicts are current
        time.sleep(min(2.0, fast_w / 2))
        stack.core.slo.tick()

        slo_report = stack.core.slo.report()
        violations = stack.merged_violations()
        core = stack.core
        # topology block (round 15): gang contiguity measured from the
        # FINAL bindings (placement-level ground truth, not per-cycle
        # commit groupings) + the engine-side counters/gauge
        app_of_name: Dict[str, str] = {}
        for _t, kind, payload in events:
            if kind == "pods":
                for (name, app, _q, _c, _m, _p) in payload:
                    app_of_name[name] = app
        gang_doms: Dict[str, set] = {}
        gang_sizes: Dict[str, int] = {}
        for pod_name, node in server.bindings:
            app = app_of_name.get(pod_name)
            if app is None:
                continue
            gang_doms.setdefault(app, set()).add(dom_of_node.get(node))
            gang_sizes[app] = gang_sizes.get(app, 0) + 1
        gangs = {a: d for a, d in gang_doms.items() if gang_sizes[a] >= 2}
        cross = sum(1 for d in gangs.values()
                    if len(d) != 1 or None in d)
        # fragmentation from the encoder's live node state, NOT the gauge:
        # with --topology false the steering path (and its gauge) never
        # runs, but the A/B artifact still needs the off-side's real
        # fragmentation or the comparison reads inverted
        from yunikorn_tpu.topology.model import fleet_fragmentation

        # the sharded front end composes per-shard aggregates (its .encoder
        # is only the primary shard's fleet slice)
        frag = (core.fleet_fragmentation()
                if hasattr(core, "fleet_fragmentation")
                else fleet_fragmentation(core.encoder.nodes))
        topo_block = {
            "mode": ("off" if args.topology == "false"
                     else ("on" if with_topology else "unlabeled")),
            "gangs": len(gangs),
            "cross_domain_gangs": cross,
            "one_domain_ratio": (round(1.0 - cross / len(gangs), 4)
                                 if gangs else 1.0),
            "fragmentation": frag,
        }
        # shards block (round 16): deterministic routing/commit facts in
        # the fingerprint (node partition and app->home-shard maps are
        # seed/hash-deterministic); the ledger's contention counters are
        # timing-dependent, so they ride `timings` instead
        if hasattr(core, "shard_report"):
            srep = core.shard_report()
            shard_block = {
                "count": srep["count"],
                "nodes_per_shard": [s["nodes"] for s in srep["shards"]],
                "bound_per_shard": [s["bound"] for s in srep["shards"]],
                "repair_placed": srep["repair"]["placed"],
                "repair_migrated": srep["repair"]["migrated"],
                "quota_violations": len(core.ledger.audit()),
            }
            # ledger reconvergence contract (round 22): audit() must come
            # back clean (quota_violations above pins it), and the
            # AGGREGATE confirmed usage at drain end is a pure function
            # of the surviving pod set — equal for a same-seed run with
            # the ledger behind the socket, even across a netsplit +
            # degraded window. (The per-tenant split is racy — which
            # queue a churned pod's replacement lands on is timing-
            # dependent — so the raw snapshot rides timings, not the
            # fingerprint.)
            lrpc = bool(getattr(core, "_ledger_rpc", False))
            usage = core.ledger.usage_snapshot()
            totals: Dict[str, int] = {}
            for items in usage.values():
                for rk, v in items.items():
                    totals[rk] = totals.get(rk, 0) + v
            shard_block["ledger"] = {"rpc": lrpc, "usage_totals": totals}
            timings["shard_ledger"] = srep["ledger"]
            timings["ledger_usage"] = usage
            timings["ledger_usage_hash"] = hashlib.sha256(json.dumps(
                usage, sort_keys=True,
                separators=(",", ":")).encode()).hexdigest()[:16]
            if lrpc:
                # RPC-plane facts are timing-dependent (how many cycles
                # landed inside the fault window) and ride timings
                timings["ledger_rpc"] = {
                    "mode": core.ledger.mode,
                    "contention_retries": core.ledger.contention_retries,
                    "degraded_admits": core.ledger.degraded_admits,
                    "degraded_rejects": core.ledger.degraded_rejects,
                    "replayed_ops": core.ledger.replayed_ops,
                    "lease_expiries": (
                        core.lease_monitor.expiries_seen
                        if core.lease_monitor is not None else 0),
                }
            if args.kill_shard >= 0:
                # which asks landed on the dying shard before the kill is
                # detection-timing-dependent: per-shard splits and repair
                # counts leave the deterministic fingerprint under a kill
                for key in ("bound_per_shard", "nodes_per_shard",
                            "repair_placed", "repair_migrated"):
                    timings[key] = shard_block.pop(key)
            fo = srep.get("failover") or {}
            if args.kill_shard >= 0 or fo.get("quarantines"):
                # the deterministic failover facts (the killed shard's
                # domain set is seed/hash-deterministic); rehome wall and
                # end-state ride `timings`
                last = fo.get("last_rehome") or {}
                shard_block["failover"] = {
                    "quarantines": fo.get("quarantines", 0),
                    "rehomed_nodes": fo.get("rehomed_nodes_total", 0),
                    "quarantined_shard": last.get("shard"),
                    "reason": last.get("reason"),
                }
                timings["failover"] = {
                    "states": fo.get("states"),
                    "last_event": fo.get("last_event"),
                    "last_rehome": last,
                }
        else:
            shard_block = {"count": 1}
        # counters merged across restarts: a rebuilt core starts at zero
        # and must neither lose nor double-count pre-restart residue
        preempt_total = stack.merged_counter("preempted_total")
        mis_evict = stack.merged_counter("mis_evictions")
        e2e = core.obs.get("pod_e2e_latency_seconds")
        timings["policy_duels"] = _duel_counts(core)
        timings["wall_s"] = round(time.time() - t_run0, 2)
        timings["restart_first_cycle_ms"] = stack.restart_first_cycle_ms
        process_block = None
        if stack.takeover_reports:
            tr = stack.takeover_reports[-1]
            # booleans in the fingerprint (the recovery contract: stable
            # across same-seed runs); the raw milliseconds ride timings
            process_block = {
                "restored_all": bool(
                    tr.get("restored_allocations", 0)
                    >= tr.get("bound_at_boot", 0)),
                "lost_bound": tr.get("lost_bound"),
                "mis_evictions": tr.get("mis_evictions"),
                "cold_verdict": tr.get("cold_verdict"),
                "measured": tr.get("first_cycle_ms") is not None,
            }
            timings["takeover"] = {
                k: tr.get(k) for k in (
                    "first_cycle_ms", "cold_budget_ms", "window_s",
                    "bound_at_boot", "bound_at_exit",
                    "restored_allocations", "aot_hits")}
        timings["bound_e2e_observations"] = (
            e2e.child_state()[0] if e2e is not None else 0)

        # ---- tracing block (round 20): merged chrome trace export,
        # journey-ledger audit, flight-recorder tally. Stable booleans in
        # the fingerprint; span/journey COUNTS are cycle-batching-
        # dependent and ride `timings` ----
        trace_doc = core.tracer.chrome_trace()
        if args.trace_out:
            with open(args.trace_out, "w") as f:
                json.dump(trace_doc, f)
            print(f"[replay] merged chrome trace written to "
                  f"{args.trace_out} ({len(trace_doc['traceEvents'])} "
                  "events)", file=sys.stderr, flush=True)
        spans_by_stage: Dict[str, int] = {}
        for ev in trace_doc["traceEvents"]:
            if ev.get("ph") == "X":
                spans_by_stage[ev["name"]] = \
                    spans_by_stage.get(ev["name"], 0) + 1
        jstats = core.journey.stats()
        # every bound trace pod must have a COMPLETE journey whose stage
        # sum tiles its measured e2e latency (the exactness contract);
        # verified in-process over the whole retained tail
        worst_err, checked = 0.0, 0
        for j in core.journey.tail(max(len(want), 64)):
            if j.get("outcome") != "bound" or not j.get("e2e_ms"):
                continue
            checked += 1
            err = (abs(sum(j["stages_ms"].values()) - j["e2e_ms"])
                   / j["e2e_ms"])
            worst_err = max(worst_err, err)
        frstats = core.flightrec.stats()
        tracing_block = {
            "trace_out": bool(args.trace_out),
            "flightrec_enabled": bool(frstats["enabled"]),
            "journeys_bound_complete": bool(
                jstats["completed"] >= len(want & bound)),
            "stage_sum_within_5pct": bool(checked and worst_err <= 0.05),
        }
        timings["tracing"] = {
            "spans_by_stage": spans_by_stage,
            "journey": jstats,
            "journeys_checked": checked,
            "stage_sum_worst_err": round(worst_err, 6),
            "recordings_by_trigger": frstats["by_trigger"],
        }

        violated = sorted(n for n, c in violations.items() if c)
        all_bound = want <= bound
        # the fresh-process restart is part of the run's pass verdict: a
        # takeover that lost bound pods, mis-evicted, missed its cold
        # budget, or never measured an admitted cycle fails the replay
        process_ok = (process_block is None
                      or (process_block["restored_all"]
                          and process_block["lost_bound"] == 0
                          and process_block["mis_evictions"] == 0
                          and process_block["measured"]
                          and process_block["cold_verdict"] == "ok"))
        report = {
            "trace": args.trace,
            "seed": args.seed,
            "nodes": args.nodes,
            "tenants": args.tenants,
            "policy": policy,
            "fault": args.fault,
            "targets": {
                "pod_e2e_p99_s": args.slo_e2e,
                "cycle_staleness_s": args.slo_staleness,
                "cold_start_budget_ms": args.slo_cold_budget_ms,
            },
            # the seeded-determinism contract: everything in `fingerprint`
            # must be identical across two runs with the same arguments
            # (the `timings` section is the explicitly excluded remainder)
            "fingerprint": {
                "trace": args.trace,
                "seed": args.seed,
                "nodes": args.nodes,
                "pods_requested": args.pods,
                "events": len(events),
                "created": created,
                "bound": int(len(want & bound)),
                "all_bound": bool(all_bound),
                "policy": policy,
                "verdicts": slo_report and {
                    k: v["verdict"]
                    for k, v in slo_report["objectives"].items()},
                "violated_objectives": violated,
                "preempted_total": preempt_total,
                "mis_evictions": mis_evict,
                "restarts": stack.restarts,
                "restart_mode": args.restart_mode,
                "process_restart": process_block,
                "topology": topo_block,
                "shards": shard_block,
                # `trace` above is the trace NAME; this is the round-20
                # observability block (merged export + journey audit)
                "tracing": tracing_block,
                # the learned-policy hash makes A/B reports seed-
                # reproducible ACROSS checkpoints (two runs only
                # fingerprint-match when the same params served); duel
                # COUNTS are cycle-batching- (timing-) dependent and ride
                # `timings` below
                "policy_checkpoint": _ckpt_hash(core),
            },
            "slo": slo_report,
            "violations": violations,
            "pass": bool(all_bound and not violated and process_ok),
            "timings": timings,
        }
        return report
    finally:
        stack.stop()
        server.stop()


def _ckpt_hash(core) -> Optional[str]:
    """Active learned-policy checkpoint hash (primary shard) or None."""
    target = getattr(core, "primary", core)
    ck = getattr(target, "_policy_ckpt", None)
    return ck.hash if ck is not None else None


def _duel_counts(core) -> Dict[str, int]:
    """Committed-winner counts per policy from the duel counter (seed-
    deterministic: the duel inputs and decision rule are)."""
    c = core.obs.get("policy_duels_total")
    if c is None:
        return {}
    out = {}
    for pol in ("greedy", "optimal", "learned"):
        won = int(c.sum_over(policy=pol, outcome="won"))
        if won:
            out[pol] = won
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", choices=TRACES, default="gang-storm")
    ap.add_argument("--nodes", type=int, default=10_000)
    ap.add_argument("--pods", type=int, default=900)
    ap.add_argument("--tenants", type=int, default=6)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--duration", type=float, default=30.0,
                    help="trace wave window seconds (drain excluded)")
    ap.add_argument("--interval", type=float, default=0.05)
    ap.add_argument("--overcommit", type=float, default=1.0,
                    help=">1.0 scales pod cpu to create contention "
                         "(preemption A/B); default fully placeable")
    ap.add_argument("--fault",
                    choices=("none", "hang", "fail", "netsplit",
                             "ledger-lag"),
                    default="none",
                    help="inject a robustness/faults.py fault mid-trace: "
                         "hang/fail act on the supervised assign path; "
                         "netsplit/ledger-lag act on the ledger RPC "
                         "transport (need --ledger-socket) — netsplit "
                         "partitions it (degraded-mode admission must "
                         "carry the storm, journal replay reconverges on "
                         "heal), ledger-lag adds 150ms per frame")
    ap.add_argument("--restart-mode", choices=("inprocess", "process"),
                    default="inprocess",
                    help="restart-storm restart shape: inprocess rebuilds "
                         "core+shim inside this interpreter; process "
                         "spawns a GENUINELY FRESH interpreter that takes "
                         "over against the live server (true process-"
                         "boundary cold start, scored vs the "
                         "aot_cold_start budget; pair with --aot-store)")
    ap.add_argument("--takeover-window", type=float, default=25.0,
                    help="seconds the fresh-process takeover child serves "
                         "before handing back (it exits early once the "
                         "cold start is measured and half the window ran)")
    ap.add_argument("--kill-shard", type=int, default=-1,
                    help="kill this shard's scheduling loop mid-trace "
                         "(needs --shards >= 2): the failover supervisor "
                         "must quarantine it and re-home its domains")
    ap.add_argument("--kill-mode", choices=("crash", "wedge", "lease"),
                    default="crash",
                    help="crash = faults.crash unwinds the loop thread; "
                         "wedge = slow fault past every dispatch deadline; "
                         "lease = host-kill drill (needs --ledger-socket): "
                         "a stale peer lease on the ledger liveness "
                         "authority expires and the HostLeaseMonitor "
                         "quarantines/re-homes the dead host's shard")
    ap.add_argument("--failover-stale", type=float, default=120.0,
                    help="robustness.failoverStaleSeconds for the replay")
    ap.add_argument("--failover-probe", type=float, default=0.5,
                    help="robustness.failoverProbeSeconds for the replay")
    ap.add_argument("--failover-rejoin", type=float, default=60.0,
                    help="robustness.failoverRejoinSeconds for the replay")
    ap.add_argument("--assert-failover", action="store_true",
                    help="with --kill-shard: exit 1 unless the killed "
                         "shard was quarantined, 100%% of its nodes "
                         "re-homed, the ledger audit stayed clean and "
                         "every pod bound")
    ap.add_argument("--ledger-socket", action="store_true",
                    help="serve the quota-ledger authority behind a local "
                         "socket (core/ledger_service.py) and couple "
                         "every shard through LedgerClient: reserve/"
                         "confirm/release ride the RPC boundary with "
                         "deadlines, idempotent replay, a circuit breaker "
                         "and degraded-mode admission (needs --shards "
                         ">= 2); the fingerprint's ledger usage hash must "
                         "stay bit-equal to the in-process run")
    ap.add_argument("--ledger-fail-closed", action="store_true",
                    help="robustness.ledgerFailClosed=true: degraded-mode "
                         "admission REJECTS while the ledger is "
                         "unreachable — pair with --fault netsplit "
                         "--expect-violation (the starvation IS the "
                         "detected violation)")
    ap.add_argument("--lease-ttl", type=float, default=6.0,
                    help="robustness.ledgerLeaseTtlSeconds for the replay "
                         "(compressed so --kill-mode lease detects the "
                         "dead peer mid-trace)")
    ap.add_argument("--quota-max-vcore", type=int, default=0,
                    help="per-tenant-queue vcore max in the trace's "
                         "queues.yaml (0 = unlimited = NO ledger "
                         "trackers): set a generous value so every pod "
                         "rides reserve/confirm/release through the quota "
                         "plane — required for the ledger chaos drills to "
                         "put real traffic on the RPC boundary")
    # --takeover*: internal (the fresh-process child)
    ap.add_argument("--takeover", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--takeover-port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--takeover-conf", default="", help=argparse.SUPPRESS)
    ap.add_argument("--policy",
                    choices=("auto", "greedy", "optimal", "learned", "all"),
                    default="auto")
    ap.add_argument("--policy-checkpoint", default="",
                    help="learned-policy checkpoint prefix (solver."
                         "policyCheckpoint) — required for the learned "
                         "policy to actually dispatch, and enables the "
                         "third --ab arm")
    ap.add_argument("--dataset-out", default="",
                    help="record every choose_plan duel the core runs as a "
                         "training dataset (policy/train.DatasetWriter "
                         "format; consumed by scripts/policy_train.py). "
                         "Needs a duel-running policy: optimal/all, or "
                         "learned with --policy-checkpoint. The writer "
                         "OWNS the dir (wipes stale cycles); --ab arms "
                         "record into per-policy subdirectories")
    ap.add_argument("--dataset-max-cycles", type=int, default=512)
    ap.add_argument("--ab", action="store_true",
                    help="replay the identical trace per policy arm — "
                         "greedy, optimal, plus learned when "
                         "--policy-checkpoint is set — and record "
                         "preemption volume + placements for each")
    ap.add_argument("--assert-quality", action="store_true",
                    help="with --ab + --policy-checkpoint: exit 1 if the "
                         "learned arm bound fewer pods than the greedy arm "
                         "(the zero-placement-loss gate)")
    ap.add_argument("--shards", type=int, default=1,
                    help="control-plane shards (core/shard.py): N >= 2 "
                         "replays the trace through N pipelined "
                         "CoreScheduler shards over disjoint node "
                         "partitions — the shard_parity dial for "
                         "gang-storm / slice-fragmentation under "
                         "--assert-slo; the report fingerprint gains a "
                         "`shards` block (per-shard bound counts, "
                         "repair-pass placements; ledger contention "
                         "retries ride `timings`)")
    ap.add_argument("--topology", choices=("auto", "true", "false"),
                    default="auto",
                    help="solver.topology for the replay (the round-15 A/B "
                         "dial: false replays the identical trace with the "
                         "pre-topology programs)")
    ap.add_argument("--topology-labels", action="store_true",
                    help="synthesize topology labels on the replay nodes "
                         "for ANY trace (slice-fragmentation always does)")
    ap.add_argument("--nodes-per-domain", type=int, default=16,
                    help="nodes per synthesized ICI domain")
    ap.add_argument("--aot-store", default=os.environ.get("YK_AOT_STORE", ""),
                    help="attach a prebuilt AOT executable store (the "
                         "restart-storm rebuild serves from it)")
    ap.add_argument("--slo-e2e", type=float, default=40.0,
                    help="pod e2e p99 target seconds (default sized for the CPU\n                         simulation env: a first-touch big-bucket program\n                         materialization is 10-20s there; tighten on real HW)")
    ap.add_argument("--slo-staleness", type=float, default=30.0,
                    help="cycle staleness target seconds (absorbs one\n                         first-touch program materialization on CPU)")
    ap.add_argument("--slo-cold-budget-ms", type=float, default=300_000.0,
                    help="first-cycle budget ms (CPU compile allowance; "
                         "tighten when replaying against an AOT store)")
    ap.add_argument("--dispatch-deadline", type=float, default=60.0,
                    help="robustness.dispatchDeadlineSeconds for the replay "
                         "(the hang fault sleeps 3x past it)")
    ap.add_argument("--warmup-timeout", type=float, default=600.0)
    ap.add_argument("--no-prewarm", action="store_true",
                    help="skip the bucket prewarm (fast small-scale runs)")
    ap.add_argument("--drain-timeout", type=float, default=180.0)
    ap.add_argument("--report", default="",
                    help="write the replay report JSON here")
    ap.add_argument("--trace-out", default="",
                    help="write the merged Chrome trace JSON here (the "
                         "fleet export: one pid per shard plus the front-"
                         "end lane; open in Perfetto)")
    ap.add_argument("--flightrec-dir", default="",
                    help="enable the triggered flight recorder "
                         "(observability.flightRecorderDir) — SLO "
                         "violations / quarantines / breaker exhaustion "
                         "dump bounded post-mortem bundles here mid-run")
    ap.add_argument("--assert-slo", action="store_true",
                    help="exit nonzero (naming the objectives) unless the "
                         "run passes: every pod bound, zero violations")
    ap.add_argument("--expect-violation", action="store_true",
                    help="exit zero ONLY if the SLO engine detected at "
                         "least one violation (chaos-detection assertion)")
    args = ap.parse_args()

    if args.takeover:
        return child_takeover(args)

    if args.kill_shard >= 0 and not (0 <= args.kill_shard < args.shards
                                     and args.shards >= 2):
        # fail at parse time, not 42% into a storm that took minutes
        print(f"[replay] FAIL: --kill-shard {args.kill_shard} needs "
              f"--shards >= 2 with the index in range (got --shards "
              f"{args.shards})", file=sys.stderr, flush=True)
        return 2
    needs_ledger = (args.fault in ("netsplit", "ledger-lag")
                    or args.kill_mode == "lease" or args.ledger_fail_closed)
    if needs_ledger and not args.ledger_socket:
        print("[replay] FAIL: --fault netsplit|ledger-lag, --kill-mode "
              "lease and --ledger-fail-closed act on the ledger RPC "
              "transport — add --ledger-socket", file=sys.stderr,
              flush=True)
        return 2
    if args.ledger_socket and args.shards < 2:
        print("[replay] FAIL: --ledger-socket needs --shards >= 2 (a "
              "single shard keeps the direct in-process ledger by "
              "contract)", file=sys.stderr, flush=True)
        return 2

    if args.ab:
        arms = ["greedy", "optimal"]
        if args.policy_checkpoint:
            arms.append("learned")
        reports = {p: run_replay(args, p) for p in arms}
        report = {
            "ab": {p: r["fingerprint"] for p, r in reports.items()},
            "preemption_volume": {
                p: r["fingerprint"]["preempted_total"]
                for p, r in reports.items()},
            "runs": reports,
            "pass": all(r["pass"] for r in reports.values()),
        }
        violated = sorted({o for r in reports.values()
                           for o in r["fingerprint"]["violated_objectives"]})
    else:
        report = run_replay(args, args.policy)
        violated = report["fingerprint"]["violated_objectives"]

    out = json.dumps(report, indent=2, default=str)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out + "\n")
        print(f"[replay] report written to {args.report}", file=sys.stderr,
              flush=True)
    print(out)

    if args.assert_quality:
        if not (args.ab and args.policy_checkpoint):
            print("[replay] FAIL: --assert-quality needs --ab plus "
                  "--policy-checkpoint (the learned arm)", file=sys.stderr,
                  flush=True)
            return 2
        g_bound = reports["greedy"]["fingerprint"]["bound"]
        l_bound = reports["learned"]["fingerprint"]["bound"]
        if l_bound < g_bound:
            print(f"[replay] FAIL: learned arm bound {l_bound} < greedy "
                  f"arm {g_bound} — the learned policy lost placements",
                  file=sys.stderr, flush=True)
            return 1
        print(f"[replay] QUALITY OK: learned arm bound {l_bound} >= "
              f"greedy arm {g_bound} (duels: "
              f"{reports['learned']['timings'].get('policy_duels')})",
              file=sys.stderr, flush=True)
    if args.assert_failover:
        if args.kill_shard < 0 or args.ab:
            print("[replay] FAIL: --assert-failover needs --kill-shard "
                  "(and no --ab)", file=sys.stderr, flush=True)
            return 2
        fp = report["fingerprint"]
        fo = (fp.get("shards") or {}).get("failover") or {}
        problems = []
        if fo.get("quarantines", 0) < 1:
            problems.append("shard was never quarantined")
        if fo.get("quarantined_shard") != args.kill_shard:
            problems.append(
                f"quarantined shard {fo.get('quarantined_shard')} != "
                f"killed shard {args.kill_shard}")
        if fo.get("rehomed_nodes", 0) < 1:
            problems.append("no nodes re-homed")
        if (fp.get("shards") or {}).get("quota_violations"):
            problems.append("ledger audit reported violations")
        if not fp.get("all_bound"):
            problems.append("not every pod bound")
        if problems:
            print(f"[replay] FAIL (failover): {'; '.join(problems)}",
                  file=sys.stderr, flush=True)
            return 1
        print(f"[replay] FAILOVER OK: shard {args.kill_shard} "
              f"({fo.get('reason')}) quarantined, "
              f"{fo.get('rehomed_nodes')} nodes re-homed, ledger clean, "
              "all pods bound", file=sys.stderr, flush=True)
    if args.expect_violation:
        if violated:
            print(f"[replay] EXPECTED violation detected: {violated}",
                  file=sys.stderr, flush=True)
            return 0
        print("[replay] FAIL: no SLO violation detected under the injected "
              "fault", file=sys.stderr, flush=True)
        return 1
    if args.assert_slo:
        ok = report["pass"]
        if not ok:
            fp = report.get("fingerprint", {})
            print(f"[replay] FAIL: violated objectives: {violated or 'none'}"
                  f" (all_bound={fp.get('all_bound')}, "
                  f"process_restart={fp.get('process_restart')})",
                  file=sys.stderr, flush=True)
            return 1
        print("[replay] PASS: all pods bound, zero SLO violations",
              file=sys.stderr, flush=True)
    return 0


def _exit(code: int) -> None:
    """Hard exit: a deadline-abandoned dispatch leaves a zombie watchdog
    thread wedged inside XLA, and interpreter teardown racing it can
    segfault AFTER the report and verdict are already out — which would
    corrupt the exit code CI gates on. Flush everything and leave."""
    try:
        from yunikorn_tpu import aot

        rt = aot.get_runtime()
        if rt is not None:
            rt.flush()
    except Exception:
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


if __name__ == "__main__":
    _exit(main())
