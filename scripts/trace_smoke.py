#!/usr/bin/env python
"""Fleet flight-recorder smoke (round 20): the end-to-end acceptance run.

Two seeded replays through scripts/trace_replay.py, asserted from their
artifacts (report JSON, merged Chrome trace, flight-recorder bundles):

1. A 4-shard gang-storm with shard 1 killed mid-storm must produce
   - ONE merged Chrome trace (--trace-out) that is valid trace-event
     JSON: >= 5 pids (4 shard lanes + the front-end lane), every
     metadata event before every data event, a process_name for every
     pid and a thread_name for every (pid, tid) that carries data —
     i.e. the file Perfetto loads without complaint;
   - a journey record for every bound pod whose stage sum tiles the
     measured e2e latency within 5% (the report's tracing block
     asserts it in-process over the full tail);
   - EXACTLY one quarantine-triggered bundle whose dead_shard_trace.json
     holds the dead shard's final cycle spans on the dead shard's pid.

2. A hang-fault run (--expect-violation) must fire EXACTLY one
   slo_violation bundle, and that bundle must round-trip: manifest.json
   parses, and every file the manifest lists parses as JSON.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPLAY = os.path.join(REPO, "scripts", "trace_replay.py")
FRONT_PID = 1  # obs/trace.py: shard k exports on pid FRONT_PID + 1 + k


def _run(args, timeout=1200):
    cmd = [sys.executable, REPLAY] + args
    print(f"[trace-smoke] $ {' '.join(cmd)}", file=sys.stderr, flush=True)
    return subprocess.run(cmd, timeout=timeout).returncode


def _fail(msg: str) -> None:
    print(f"[trace-smoke] FAIL: {msg}", file=sys.stderr, flush=True)
    sys.exit(1)


def _check_chrome_trace(path: str, min_pids: int) -> dict:
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        _fail(f"{path}: no traceEvents")
    metas = [i for i, e in enumerate(evs) if e.get("ph") == "M"]
    datas = [i for i, e in enumerate(evs) if e.get("ph") != "M"]
    if not datas:
        _fail(f"{path}: metadata only, no data events")
    if max(metas) > min(datas):
        _fail(f"{path}: metadata event after a data event (Perfetto "
              "names tracks from metadata seen BEFORE the data)")
    pids = {e["pid"] for e in evs}
    if len(pids) < min_pids:
        _fail(f"{path}: {len(pids)} pids {sorted(pids)} < {min_pids} "
              "(expected one per shard + the front-end lane)")
    named = {e["pid"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    if pids - named:
        _fail(f"{path}: pids without process_name metadata: "
              f"{sorted(pids - named)}")
    tid_named = {(e["pid"], e.get("tid")) for e in evs
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    data_lanes = {(e["pid"], e.get("tid")) for e in evs
                  if e.get("ph") == "X"}
    if data_lanes - tid_named:
        _fail(f"{path}: data lanes without thread_name metadata: "
              f"{sorted(data_lanes - tid_named)}")
    for e in evs:
        if e.get("ph") == "X" and (e.get("ts") is None
                                   or e.get("dur", -1) < 0):
            _fail(f"{path}: malformed complete event {e}")
    return doc


def _bundles(d: str, trigger: str):
    return sorted(b for b in os.listdir(d)
                  if b.startswith("rec-") and b.endswith("-" + trigger))


def _check_bundle_roundtrip(path: str) -> dict:
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    for fname in manifest.get("files", []):
        with open(os.path.join(path, fname)) as f:
            json.load(f)
    return manifest


def main() -> int:
    t0 = time.time()
    work = tempfile.mkdtemp(prefix="yk_trace_smoke_")
    trace_out = os.path.join(work, "fleet_trace.json")
    report_a = os.path.join(work, "report_kill.json")
    report_b = os.path.join(work, "report_hang.json")
    frec_a = os.path.join(work, "frec_kill")
    frec_b = os.path.join(work, "frec_hang")
    os.makedirs(frec_a)
    os.makedirs(frec_b)
    try:
        # ---- run 1: 4 shards, kill shard 1 mid-storm ----
        rc = _run(["--trace", "gang-storm", "--nodes", "400",
                   "--pods", "320", "--tenants", "4", "--duration", "12",
                   "--shards", "4", "--kill-shard", "1",
                   "--failover-stale", "30", "--failover-probe", "0.3",
                   "--assert-failover",
                   "--trace-out", trace_out, "--flightrec-dir", frec_a,
                   "--report", report_a])
        if rc != 0:
            _fail(f"kill-shard replay exited {rc}")
        with open(report_a) as f:
            rep = json.load(f)
        tracing = rep["fingerprint"]["tracing"]
        if not tracing["flightrec_enabled"]:
            _fail("flight recorder disabled in replay despite "
                  "--flightrec-dir (conf wiring broke)")
        if not tracing["journeys_bound_complete"]:
            _fail(f"journey ledger incomplete: "
                  f"{rep['timings'].get('tracing')}")
        if not tracing["stage_sum_within_5pct"]:
            _fail(f"journey stage sums do not tile the e2e latency: "
                  f"{rep['timings'].get('tracing')}")

        doc = _check_chrome_trace(trace_out, min_pids=5)
        dead_pid = FRONT_PID + 1 + 1  # shard 1's stable lane
        front_names = {e["name"] for e in doc["traceEvents"]
                       if e.get("ph") == "X" and e["pid"] == FRONT_PID}
        if "route" not in front_names:
            _fail(f"front-end lane has no route spans (got "
                  f"{sorted(front_names)})")

        quar = _bundles(frec_a, "quarantine")
        if len(quar) != 1:
            _fail(f"expected exactly 1 quarantine bundle, got {quar}")
        bundle = os.path.join(frec_a, quar[0])
        manifest = _check_bundle_roundtrip(bundle)
        if "dead_shard_trace.json" not in manifest.get("files", []):
            _fail(f"quarantine bundle missing dead_shard_trace.json: "
                  f"{manifest.get('files')}")
        with open(os.path.join(bundle, "dead_shard_trace.json")) as f:
            dead = json.load(f)
        devs = [e for e in dead["traceEvents"] if e.get("ph") == "X"]
        if not devs:
            _fail("dead_shard_trace.json holds no spans — the freeze "
                  "must run BEFORE the engine detaches")
        wrong = {e["pid"] for e in devs} - {dead_pid}
        if wrong:
            _fail(f"dead shard spans on wrong pids {wrong} "
                  f"(expected {dead_pid})")
        print(f"[trace-smoke] kill-shard run OK: trace "
              f"{len(doc['traceEvents'])} events / "
              f"{len({e['pid'] for e in doc['traceEvents']})} pids, "
              f"dead-shard snapshot {len(devs)} spans, journeys exact",
              file=sys.stderr, flush=True)

        # ---- run 2: hang fault -> exactly one slo_violation bundle ----
        rc = _run(["--trace", "gang-storm", "--nodes", "400",
                   "--pods", "320", "--tenants", "4", "--duration", "12",
                   "--fault", "hang", "--slo-staleness", "4",
                   "--expect-violation",
                   "--flightrec-dir", frec_b, "--report", report_b])
        if rc != 0:
            _fail(f"hang-fault replay exited {rc}")
        slo = _bundles(frec_b, "slo_violation")
        if len(slo) != 1:
            _fail(f"expected exactly 1 slo_violation bundle, got {slo} "
                  f"(all: {sorted(os.listdir(frec_b))})")
        manifest = _check_bundle_roundtrip(os.path.join(frec_b, slo[0]))
        if "slo_violation" not in manifest.get("trigger", ""):
            _fail(f"bundle manifest trigger {manifest.get('trigger')!r}")
        for want in ("trace.json", "metrics.json", "journeys.json"):
            if want not in manifest.get("files", []):
                _fail(f"slo_violation bundle missing {want}: "
                      f"{manifest.get('files')}")
        print(f"[trace-smoke] hang-fault run OK: one slo_violation "
              f"bundle ({len(manifest['files'])} files), round-trips",
              file=sys.stderr, flush=True)

        print(f"trace-smoke OK in {time.time() - t0:.1f}s: merged fleet "
              "trace valid, journeys exact, quarantine + slo_violation "
              "bundles fired exactly once each and round-trip",
              flush=True)
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
