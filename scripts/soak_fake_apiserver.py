#!/usr/bin/env python
"""Chaos soak: the full scheduler stack against the in-process API server.

The live-cluster counterpart of this run is documented in
deployments/kwok-perf-test/ (kwok-setup.sh + deploy-tool.sh +
run-scheduler.sh); this script is the build-environment substitute the
round-2 verdict asked for — the REAL adapter (client/kube.py reflectors over
HTTP) driving the shim + core for a sustained churn window while the API
server misbehaves:

  - watch streams killed mid-event every few seconds (reflector resume)
  - event-log compactions forcing 410 Gone relists
  - pods completing and arriving throughout

At the end, every created pod must be bound exactly once, the scheduler's
cache must agree with the API server's state, and no informer may have died.

Usage:
    python scripts/soak_fake_apiserver.py [--pods 2000] [--nodes 200]
        [--duration 60] [--chaos-interval 3]

Exit code 0 = soak passed. A run log is printed to stdout.
"""
from __future__ import annotations

import argparse
import os
import random
import ssl
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yunikorn_tpu.utils.jaxtools import force_cpu_platform


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=2000)
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="churn window seconds (excludes drain)")
    ap.add_argument("--chaos-interval", type=float, default=3.0)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    rng = random.Random(args.seed)

    force_cpu_platform(8)

    from tests.fake_apiserver import FakeAPIServer
    from yunikorn_tpu.cache.context import Context
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider
    from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
    from yunikorn_tpu.shim.scheduler import KubernetesShim

    t_start = time.time()
    server = FakeAPIServer()
    port = server.start()
    print(f"[soak] fake apiserver on :{port}")

    for i in range(args.nodes):
        server.add_node_doc(f"soak-n{i}", cpu="16", memory="64Gi")

    reset_for_tests()
    get_holder().update_config_maps(
        [{"service.schedulingInterval": "0.05"}], initial=True)
    dispatch_mod.reset_dispatcher()
    cfg = KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context())
    provider = RealAPIProvider(cfg)
    cache = SchedulerCache()
    core = CoreScheduler(cache, interval=0.05)
    ctx = Context(provider, core, cache=cache)
    shim = KubernetesShim(provider, core, context=ctx)
    core.start()
    shim.run()
    print(f"[soak] scheduler up ({args.nodes} nodes) "
          f"t+{time.time() - t_start:.1f}s")

    stop = threading.Event()
    chaos_counts = {"kill": 0, "compact": 0}

    def chaos():
        while not stop.wait(args.chaos_interval):
            if rng.random() < 0.5:
                n = server.kill_watches()
                chaos_counts["kill"] += 1
                print(f"[chaos] killed {n} watch streams")
            else:
                coll = rng.choice(["pods", "nodes", "configmaps"])
                server.compact(coll)
                server.kill_watches(coll)
                chaos_counts["compact"] += 1
                print(f"[chaos] compacted {coll} (410 storm on reconnect)")

    chaos_thread = threading.Thread(target=chaos, daemon=True)
    chaos_thread.start()

    created = 0
    completed = 0
    deadline = time.time() + args.duration
    batch = max(args.pods // max(int(args.duration), 1), 1)
    while time.time() < deadline and created < args.pods:
        for _ in range(min(batch, args.pods - created)):
            server.add_pod_doc(f"soak-p{created}", app_id=f"soak-app-{created % 8}",
                               cpu="100m", memory="64Mi")
            created += 1
        # complete a slice of already-bound pods (kubelet finishing work):
        # exercises the release/accounting paths under the same chaos
        bound_now = [name for name, _ in server.bindings]
        for name in bound_now[completed: completed + batch // 4]:
            with server._lock:
                doc = server.store["pods"].get(f"default/{name}")
            if doc is not None:
                doc = dict(doc)
                doc.setdefault("status", {})["phase"] = "Succeeded"
                server.add("pods", doc)
                completed += 1
        time.sleep(1.0)
        print(f"[soak] t+{time.time() - t_start:.1f}s created={created} "
              f"bound={len(server.bindings)}")

    stop.set()
    chaos_thread.join(timeout=5)

    # drain: everything created must end up bound despite the chaos
    drain_deadline = time.time() + 120
    while time.time() < drain_deadline and len(server.bindings) < created:
        time.sleep(0.5)
    ok = True
    bound_names = [n for n, _ in server.bindings]
    if len(server.bindings) < created:
        print(f"[soak] FAIL: only {len(server.bindings)}/{created} pods bound")
        ok = False
    if len(set(bound_names)) != len(bound_names):
        dupes = len(bound_names) - len(set(bound_names))
        print(f"[soak] FAIL: {dupes} pods bound more than once")
        ok = False
    # adapter stores must converge to the server's state
    time.sleep(1.0)
    adapter_pods = len(provider.list_pods())
    server_pods = len(server.store["pods"])
    if adapter_pods != server_pods:
        print(f"[soak] FAIL: adapter sees {adapter_pods} pods, "
              f"server holds {server_pods}")
        ok = False

    core.stop()
    shim.stop()
    provider.stop()
    server.stop()
    print(f"[soak] {'PASS' if ok else 'FAIL'}: {created} pods, "
          f"{len(server.bindings)} bindings, "
          f"{chaos_counts['kill']} watch kills, "
          f"{chaos_counts['compact']} 410 storms, "
          f"{time.time() - t_start:.1f}s total")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
