#!/usr/bin/env python
"""Shard-failover bench: time-to-rehome and time-to-recover when one shard
of an N-shard control plane dies at fleet scale.

Direct-API harness (no shim): M nodes registered through the sharded front
end, a working set of pods bound across every shard, then ONE shard is
quarantined (the detection path is probe-cadence-bound and configurable;
this bench measures the part that scales with fleet size — the quarantine
TRANSACTION: ledger reconcile + app re-homing + allocation re-attribution
+ whole-domain node re-homing + parked-ask re-admission) and the fleet
drains the re-admitted asks. Reported per shard count:

  quarantine_s   wall of quarantine_shard() — detection to every domain
                 re-homed and every parked ask re-submitted
  recover_s      additional wall until every parked ask is bound again
  rehomed_nodes  nodes moved off the dead shard (must be ALL it owned)
  audit          GlobalQuotaLedger.audit() after each phase (must be [])

Usage:
  python scripts/failover_bench.py --nodes 10000 --shards 4,8 --pods 1024
  python scripts/failover_bench.py --nodes 2000 --shards 4 --assert
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(n_shards: int, n_nodes: int, n_pods: int,
            interval: float = 0.05) -> dict:
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.common.objects import make_node, make_pod
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationAsk,
        AllocationRequest,
        ApplicationRequest,
        NodeAction,
        NodeInfo,
        NodeRequest,
        RegisterResourceManagerRequest,
        ResourceManagerCallback,
        UserGroupInfo,
    )
    from yunikorn_tpu.core.shard import ShardedCoreScheduler
    from yunikorn_tpu.robustness.failover import FailoverOptions

    class Recorder(ResourceManagerCallback):
        def __init__(self):
            self.bound = set()

        def update_allocation(self, response):
            for a in response.new:
                self.bound.add(a.allocation_key)

        def update_application(self, response):
            pass

        def update_node(self, response):
            pass

        def predicates(self, args):
            return None

        def preemption_predicates(self, args):
            return []

        def send_event(self, events):
            pass

        def update_container_scheduling_state(self, request):
            pass

        def get_state_dump(self):
            return "{}"

    cache = SchedulerCache()
    cb = Recorder()
    front = ShardedCoreScheduler(
        cache, n_shards, interval=interval,
        failover_options=FailoverOptions(stale_budget_s=3600.0,
                                         probe_interval_s=3600.0,
                                         rejoin_after_s=3600.0))
    front.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="bench", policy_group="queues",
                                      config=""), cb)
    t0 = time.time()
    infos = []
    for i in range(n_nodes):
        node = make_node(f"bn-{i}", cpu_milli=8000)
        cache.update_node(node)
        infos.append(NodeInfo(node_id=node.name, action=NodeAction.CREATE,
                              node=node))
    front.update_node(NodeRequest(nodes=infos))
    t_reg = time.time() - t0
    front.start()
    try:
        # ---- working set: pods bound across every shard (warm phase) ----
        apps = [f"bapp-{i}" for i in range(max(n_shards * 4, 16))]
        for app in apps:
            front.update_application(ApplicationRequest(new=[
                AddApplicationRequest(
                    application_id=app, queue_name="root.default",
                    user=UserGroupInfo(user="bench", groups=[]))]))
        keys = []
        for i in range(n_pods):
            app = apps[i % len(apps)]
            pod = make_pod(f"bp-{i}", cpu_milli=200, memory=2 ** 27)
            key = f"bp-{i}"
            keys.append(key)
            front.update_allocation(AllocationRequest(asks=[AllocationAsk(
                allocation_key=key, application_id=app,
                resource=get_pod_resource(pod), pod=pod)]))
        deadline = time.time() + 900
        while time.time() < deadline and len(cb.bound) < n_pods:
            time.sleep(0.25)
        warm_bound = len(cb.bound)
        victim = 1 % n_shards
        owned_before = front.fanout.count_for(victim)

        # ---- a second wave lands and the shard dies MID-STREAM: some of
        #      these asks are pending on the victim when it goes ----
        wave2 = max(n_pods // 4, n_shards * 8)
        for i in range(wave2):
            app = apps[i % len(apps)]
            pod = make_pod(f"bw-{i}", cpu_milli=200, memory=2 ** 27)
            key = f"bw-{i}"
            keys.append(key)
            front.update_allocation(AllocationRequest(asks=[AllocationAsk(
                allocation_key=key, application_id=app,
                resource=get_pod_resource(pod), pod=pod)]))
        parked_before = sum(
            1 for k, h in front._ask_home.items()
            if h == victim and k not in front._alloc_shard)

        # ---- the measured transaction ----
        t_q0 = time.time()
        ok = front.quarantine_shard(victim, "bench")
        quarantine_s = time.time() - t_q0
        audit_after_q = front.ledger.audit()

        # ---- recovery drain: every ask bound again ----
        t_r0 = time.time()
        deadline = time.time() + 600
        while time.time() < deadline and len(cb.bound) < len(keys):
            time.sleep(0.2)
        recover_s = time.time() - t_r0
        return {
            "shards": n_shards,
            "nodes": n_nodes,
            "pods": n_pods,
            "node_registration_s": round(t_reg, 2),
            "warm_bound": warm_bound,
            "owned_before": owned_before,
            "parked_before": parked_before,
            "quarantine_ok": bool(ok),
            "quarantine_s": round(quarantine_s, 3),
            "rehomed_nodes": front._rehomed_nodes_total,
            "recover_s": round(recover_s, 2),
            "bound_total": len(cb.bound),
            "all_bound": len(cb.bound) >= len(keys),
            "audit_after_quarantine": audit_after_q,
            "audit_final": front.ledger.audit(),
        }
    finally:
        front.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=10_000)
    ap.add_argument("--shards", default="4,8")
    ap.add_argument("--pods", type=int, default=1024)
    ap.add_argument("--interval", type=float, default=0.05)
    ap.add_argument("--assert", dest="assert_", action="store_true",
                    help="exit 1 unless every run re-homed 100%% of the "
                         "dead shard's nodes, re-bound every pod and kept "
                         "the ledger audit clean")
    ap.add_argument("--report", default="")
    args = ap.parse_args()

    results = []
    for n in (int(s) for s in args.shards.split(",")):
        print(f"[failover-bench] {n} shards x {args.nodes} nodes x "
              f"{args.pods} pods", file=sys.stderr, flush=True)
        r = run_one(n, args.nodes, args.pods, interval=args.interval)
        print(json.dumps(r), file=sys.stderr, flush=True)
        results.append(r)
    out = json.dumps({"runs": results}, indent=2)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out + "\n")
    print(out)
    if args.assert_:
        for r in results:
            ok = (r["quarantine_ok"] and r["all_bound"]
                  and r["rehomed_nodes"] >= r["owned_before"]
                  and not r["audit_after_quarantine"]
                  and not r["audit_final"])
            if not ok:
                print(f"[failover-bench] FAIL at {r['shards']} shards: {r}",
                      file=sys.stderr, flush=True)
                return 1
        print("[failover-bench] PASS", file=sys.stderr, flush=True)
    return 0


def _exit(code: int) -> None:
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


if __name__ == "__main__":
    _exit(main())
