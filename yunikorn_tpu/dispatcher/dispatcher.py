"""The central event dispatcher.

Role-equivalent to pkg/dispatcher/dispatcher.go: a singleton with typed handlers
for Application / Task / Node / Scheduler events (:40-46), a large buffered channel
(capacity = conf EventChannelCapacity, default 1,048,576), non-blocking enqueue with
an async-retry fallback (retry every 3s up to DispatchTimeout, :157-201), a hard
failure when the number of in-flight async retries exceeds max(10000, cap/10)
(:73,176-180), and a single consumer thread that routes by event type (:220-242).

The single consumer is the concurrency linchpin: events for any one object are
processed serially, so the FSMs never race. The TPU solver runs outside this
thread; its results re-enter through dispatched events, same as the reference's
core callbacks do.
"""
from __future__ import annotations

import enum
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from yunikorn_tpu.common.events import (
    ApplicationEvent,
    SchedulerNodeEvent,
    SchedulingEvent,
    TaskEvent,
)
from yunikorn_tpu.log.logger import log

logger = log("dispatcher")

ASYNC_RETRY_INTERVAL = 3.0


class EventType(enum.Enum):
    APPLICATION = 1
    TASK = 2
    NODE = 3
    SCHEDULER = 4


class DispatchError(RuntimeError):
    pass


class Dispatcher:
    def __init__(self, capacity: int = 1024 * 1024, dispatch_timeout: float = 300.0):
        self._queue: "queue.Queue[Optional[SchedulingEvent]]" = queue.Queue(maxsize=capacity)
        self._handlers: Dict[EventType, List[Callable[[SchedulingEvent], None]]] = {}
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dispatch_timeout = dispatch_timeout
        self._async_limit = max(10000, capacity // 10)
        self._inflight_async = 0
        self._drained = threading.Event()
        self._drained.set()

    # -- registration -------------------------------------------------------
    def register_event_handler(self, name: str, event_type: EventType,
                               handler: Callable[[SchedulingEvent], None]) -> None:
        with self._lock:
            self._handlers.setdefault(event_type, []).append(handler)
        logger.debug("registered event handler %s for %s", name, event_type)

    def unregister_all(self) -> None:
        with self._lock:
            self._handlers.clear()

    # -- dispatch -----------------------------------------------------------
    def dispatch(self, event: SchedulingEvent) -> None:
        """Non-blocking enqueue; falls back to an async retry thread when full."""
        if not self._running.is_set():
            raise DispatchError("dispatcher is not running")
        self._drained.clear()
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            with self._lock:
                if self._inflight_async >= self._async_limit:
                    raise DispatchError(
                        f"dispatcher exceeded async-dispatch limit {self._async_limit}"
                    )
                self._inflight_async += 1
            t = threading.Thread(target=self._async_retry, args=(event,), daemon=True)
            t.start()

    def _async_retry(self, event: SchedulingEvent) -> None:
        deadline = time.time() + self._dispatch_timeout
        try:
            while self._running.is_set():
                try:
                    self._queue.put(event, timeout=ASYNC_RETRY_INTERVAL)
                    return
                except queue.Full:
                    if time.time() > deadline:
                        logger.error("dispatch timeout for event %s", event)
                        return
        finally:
            with self._lock:
                self._inflight_async -= 1

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._running.is_set():
            return
        self._running.set()
        self._thread = threading.Thread(target=self._run, name="dispatcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop the consumer after draining what is already queued."""
        if not self._running.is_set():
            return
        self._running.clear()
        self._queue.put(None)  # wake the consumer
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and the consumer is idle (test helper)."""
        return self._drained.wait(timeout=timeout)

    def _run(self) -> None:
        while True:
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                if self._queue.unfinished_tasks == 0:
                    self._drained.set()
                if not self._running.is_set():
                    return
                continue
            if event is None:
                self._queue.task_done()
                if not self._running.is_set() and self._queue.empty():
                    self._drained.set()
                    return
                continue
            try:
                self._route(event)
            except Exception:
                logger.exception("event handler failed for %s", event)
            finally:
                self._queue.task_done()
                if self._queue.unfinished_tasks == 0:
                    self._drained.set()

    def _route(self, event: SchedulingEvent) -> None:
        if isinstance(event, ApplicationEvent):
            etype = EventType.APPLICATION
        elif isinstance(event, TaskEvent):
            etype = EventType.TASK
        elif isinstance(event, SchedulerNodeEvent):
            etype = EventType.NODE
        else:
            etype = EventType.SCHEDULER
        with self._lock:
            handlers = list(self._handlers.get(etype, ()))
        if not handlers:
            logger.warning("no handler registered for %s event %s", etype, event)
        for h in handlers:
            h(event)


# ---------------------------------------------------------------------------
# Module-level singleton (the reference dispatcher is package-global)
# ---------------------------------------------------------------------------

_instance: Optional[Dispatcher] = None
_instance_lock = threading.Lock()


def get_dispatcher() -> Dispatcher:
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = Dispatcher()
        return _instance


def reset_dispatcher(capacity: int = 1024 * 1024, dispatch_timeout: float = 300.0) -> Dispatcher:
    """Replace the singleton (tests); stops any previous instance."""
    global _instance
    with _instance_lock:
        if _instance is not None:
            _instance.stop()
        _instance = Dispatcher(capacity=capacity, dispatch_timeout=dispatch_timeout)
        return _instance


def dispatch(event: SchedulingEvent) -> None:
    get_dispatcher().dispatch(event)


def register_event_handler(name: str, event_type: EventType,
                           handler: Callable[[SchedulingEvent], None]) -> None:
    get_dispatcher().register_event_handler(name, event_type, handler)
