"""The central event dispatcher.

Role-equivalent to pkg/dispatcher/dispatcher.go: a singleton with typed handlers
for Application / Task / Node / Scheduler events (:40-46), a large buffered channel
(capacity = conf EventChannelCapacity, default 1,048,576), non-blocking enqueue with
an async-retry fallback (retry every 3s up to DispatchTimeout, :157-201), a hard
failure when the number of queued async retries exceeds max(10000, cap/10)
(:73,176-180), and a single consumer thread that routes by event type (:220-242).

Where the reference spawns one goroutine per overflow event (cheap in Go),
here overflow events queue onto ONE retry worker — 10k Python threads would
kill the process, and a single worker additionally preserves FIFO order among
the overflowed events.

The single consumer is the concurrency linchpin: events for any one object are
processed serially, so the FSMs never race. The TPU solver runs outside this
thread; its results re-enter through dispatched events, same as the reference's
core callbacks do.

Throughput note: the consumer drains the buffer in BATCHES (one condition
round-trip per batch, not per event) and routes against an immutable handler
snapshot (no lock per event). At 50k pods a bind cycle pushes ~150k events
through here — per-event lock traffic was a measured chunk of the shim's
host-bound e2e cost.
"""
from __future__ import annotations

import collections
import enum
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from yunikorn_tpu.locking import locking

from yunikorn_tpu.common.events import (
    ApplicationEvent,
    SchedulerNodeEvent,
    SchedulingEvent,
    TaskEvent,
)
from yunikorn_tpu.log.logger import log

logger = log("dispatcher")

ASYNC_RETRY_INTERVAL = 3.0


class EventType(enum.Enum):
    APPLICATION = 1
    TASK = 2
    NODE = 3
    SCHEDULER = 4


class DispatchError(RuntimeError):
    pass


class Dispatcher:
    def __init__(self, capacity: int = 1024 * 1024, dispatch_timeout: float = 300.0):
        # single condition guards the buffer; the consumer swaps the whole
        # deque out per wakeup, so producers and consumer pay one lock
        # round-trip per BATCH instead of ~4 per event (queue.Queue's
        # put/get/task_done/join accounting)
        self._buf: Deque[SchedulingEvent] = collections.deque()
        self._cond = threading.Condition()
        self._capacity = capacity
        self._processing = False            # consumer holds a swapped batch
        self._handlers: Dict[EventType, List[Callable[[SchedulingEvent], None]]] = {}
        self._snapshot: Dict[EventType, tuple] = {}
        self._lock = locking.Mutex()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dispatch_timeout = dispatch_timeout
        self._async_limit = max(10000, capacity // 10)
        # overflow events wait here for the single retry worker (FIFO)
        self._overflow: Deque[Tuple[SchedulingEvent, float]] = collections.deque()
        self._overflow_cond = threading.Condition()
        self._retry_thread: Optional[threading.Thread] = None
        # observability (attach_metrics): None until a registry attaches, so
        # the dispatch hot path pays a single attribute check when unwired
        self._m_events = None
        self._m_overflow = None
        self._m_batch = None
        self._m_depth = None
        self._m_dropped = None
        # drops counted even before a registry attaches (health/tests)
        self.dropped_count = 0

    # -- observability ------------------------------------------------------
    def attach_metrics(self, registry) -> None:
        """Register dispatcher throughput/backlog metrics into an
        obs.metrics.MetricsRegistry (the shim wires the core's registry in).
        Event-type counting is tallied per consumer BATCH, not per event —
        a 50k-pod bind cycle pushes ~150k events through here and per-event
        counter locking was exactly the kind of hot-path drag the batched
        consumer exists to avoid."""
        from yunikorn_tpu.obs.metrics import COUNT_BUCKETS

        self._m_events = registry.counter(
            "dispatcher_events_total", "events routed by the dispatcher",
            labelnames=("type",))
        self._m_overflow = registry.counter(
            "dispatcher_overflow_total",
            "events that missed the buffer and queued on the retry worker")
        self._m_batch = registry.histogram(
            "dispatcher_batch_events", "events drained per consumer wakeup",
            buckets=COUNT_BUCKETS)
        self._m_depth = registry.gauge(
            "dispatcher_queue_depth",
            "events still queued (buffer + overflow) after the last drain")
        self._m_dropped = registry.counter(
            "dispatch_dropped_total",
            "overflow events dropped because their dispatch timeout expired "
            "before buffer space freed (reference: DispatchTimeout)")
        if self.dropped_count:
            # drops that happened before the registry attached still count
            self._m_dropped.inc(self.dropped_count)

    # -- registration -------------------------------------------------------
    def register_event_handler(self, name: str, event_type: EventType,
                               handler: Callable[[SchedulingEvent], None]) -> None:
        with self._lock:
            self._handlers.setdefault(event_type, []).append(handler)
            # copy-on-write snapshot: _route reads it without any lock
            self._snapshot = {k: tuple(v) for k, v in self._handlers.items()}
        logger.debug("registered event handler %s for %s", name, event_type)

    def unregister_all(self) -> None:
        with self._lock:
            self._handlers.clear()
            self._snapshot = {}

    # -- dispatch -----------------------------------------------------------
    def dispatch(self, event: SchedulingEvent) -> None:
        """Non-blocking enqueue; overflow queues onto the single retry worker."""
        if not self._running.is_set():
            raise DispatchError("dispatcher is not running")
        with self._cond:
            if len(self._buf) < self._capacity:
                self._buf.append(event)
                self._cond.notify()
                return
        with self._overflow_cond:
            if len(self._overflow) >= self._async_limit:
                raise DispatchError(
                    f"dispatcher exceeded async-dispatch limit {self._async_limit}"
                )
            self._overflow.append((event, time.time() + self._dispatch_timeout))
            self._overflow_cond.notify()
        if self._m_overflow is not None:
            self._m_overflow.inc()

    def _retry_loop(self) -> None:
        """Single worker: drains the overflow deque into the main buffer in
        FIFO order, dropping events whose dispatch timeout passed."""
        while self._running.is_set():
            with self._overflow_cond:
                while not self._overflow and self._running.is_set():
                    self._overflow_cond.wait(timeout=ASYNC_RETRY_INTERVAL)
                if not self._running.is_set():
                    return
                event, deadline = self._overflow[0]
            pushed = False
            with self._cond:
                if len(self._buf) >= self._capacity:
                    # the consumer notifies after swapping a batch out, so
                    # this wakes as soon as space frees (bounded by the retry
                    # interval for safety)
                    self._cond.wait(timeout=ASYNC_RETRY_INTERVAL)
                if len(self._buf) < self._capacity:
                    self._buf.append(event)
                    self._cond.notify_all()
                    pushed = True
            if pushed:
                with self._overflow_cond:
                    # single popper: only this worker ever removes entries
                    self._overflow.popleft()
            elif time.time() > deadline:
                # the drop is COUNTED, not only logged: a deadline-expired
                # event is lost work (an FSM transition that never fires)
                # and must be visible on a dashboard, not only in the log
                logger.error("dispatch timeout for event %s", event)
                self.dropped_count += 1
                if self._m_dropped is not None:
                    self._m_dropped.inc()
                with self._overflow_cond:
                    self._overflow.popleft()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._running.is_set():
            return
        self._running.set()
        self._thread = threading.Thread(target=self._run, name="dispatcher", daemon=True)
        self._thread.start()
        self._retry_thread = threading.Thread(
            target=self._retry_loop, name="dispatcher-retry", daemon=True)
        self._retry_thread.start()

    def stop(self) -> None:
        """Stop the consumer after draining what is already queued."""
        if not self._running.is_set():
            return
        self._running.clear()
        with self._overflow_cond:
            self._overflow_cond.notify_all()  # wake the retry worker to exit
        with self._cond:
            self._cond.notify_all()           # wake the consumer
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._retry_thread is not None:
            self._retry_thread.join(timeout=10)
            self._retry_thread = None

    def backlog(self) -> Tuple[int, int]:
        """(buffered, overflow) depths — the health monitor's event-plane
        probe (robustness/health.dispatcher_source)."""
        with self._cond:
            buffered = len(self._buf)
        with self._overflow_cond:
            overflow = len(self._overflow)
        return buffered, overflow

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the overflow deque and buffer are empty and the
        consumer is idle (test helper)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._overflow_cond:
                overflow_empty = not self._overflow
            with self._cond:
                idle = not self._buf and not self._processing
            if overflow_empty and idle:
                with self._overflow_cond:
                    if not self._overflow:  # nothing slipped in meanwhile
                        return True
            time.sleep(0.01)
        return False

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._buf and self._running.is_set():
                    self._cond.wait(timeout=0.1)
                if not self._buf:
                    if not self._running.is_set():
                        return
                    continue
                batch = self._buf
                self._buf = collections.deque()
                self._processing = True
                self._cond.notify_all()   # space freed: wake the retry worker
            tally: Dict[str, int] = {}
            for event in batch:
                try:
                    etype = self._route(event)
                    tally[etype] = tally.get(etype, 0) + 1
                except Exception:
                    logger.exception("event handler failed for %s", event)
            if self._m_batch is not None:
                self._m_batch.observe(len(batch))
                for etype, n in tally.items():
                    self._m_events.inc(n, type=etype)
                # backlog = what is STILL waiting after this drain (events
                # that arrived mid-processing + the overflow deque) — the
                # batch size is throughput, not depth
                with self._overflow_cond:
                    backlog = len(self._overflow)
            else:
                backlog = None
            with self._cond:
                self._processing = False
                if backlog is not None:
                    backlog += len(self._buf)
            if backlog is not None:
                self._m_depth.set(backlog)

    def _route(self, event: SchedulingEvent) -> str:
        if isinstance(event, ApplicationEvent):
            etype = EventType.APPLICATION
        elif isinstance(event, TaskEvent):
            etype = EventType.TASK
        elif isinstance(event, SchedulerNodeEvent):
            etype = EventType.NODE
        else:
            etype = EventType.SCHEDULER
        handlers = self._snapshot.get(etype, ())
        if not handlers:
            logger.warning("no handler registered for %s event %s", etype, event)
        for h in handlers:
            h(event)
        return etype.name.lower()


# ---------------------------------------------------------------------------
# Module-level singleton (the reference dispatcher is package-global)
# ---------------------------------------------------------------------------

_instance: Optional[Dispatcher] = None
_instance_lock = locking.Mutex()


def get_dispatcher() -> Dispatcher:
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = Dispatcher()
        return _instance


def reset_dispatcher(capacity: int = 1024 * 1024, dispatch_timeout: float = 300.0) -> Dispatcher:
    """Replace the singleton (tests); stops any previous instance."""
    global _instance
    with _instance_lock:
        if _instance is not None:
            _instance.stop()
        _instance = Dispatcher(capacity=capacity, dispatch_timeout=dispatch_timeout)
        return _instance


def dispatch(event: SchedulingEvent) -> None:
    get_dispatcher().dispatch(event)


def register_event_handler(name: str, event_type: EventType,
                           handler: Callable[[SchedulingEvent], None]) -> None:
    get_dispatcher().register_event_handler(name, event_type, handler)
