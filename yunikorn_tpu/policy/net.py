"""The two-tower policy scorer and its versioned checkpoint format.

DOPPLER-style dual policies (arXiv 2505.23131) factor an assignment score
into per-side embeddings; here a pod tower and a node tower (tiny tanh MLPs,
plain pytree params — flax-free, so the params ride straight into the jitted
solve as traced leaves) meet in a dot product:

    score[i, m] = pod_tower(pod_feats[i]) . node_tower(node_feats[m])

The bilinear family covers the structural wins the greedy scalar score
cannot express — request/free shape alignment and per-resource pricing — at
a per-chunk cost of one [C, H] x [H, M] matmul, MXU-shaped like the rest of
the solve.

UNTRAINED-IS-INERT CONTRACT: `init_params` zero-initializes the pod tower's
output layer, so an untrained net scores exactly 0.0 for every (pod, node)
pair, and the solver's learned branch is arithmetically bit-identical to the
greedy program (the gate in ops/assign._learned_chunk_pass needs a strictly
positive advantage, and the additive term is zero). A freshly-initialized or
garbage-zero checkpoint therefore commits plans bit-identical to greedy —
pinned by tests/test_policy.py.

Checkpoints are a `.npz` of named leaves plus a JSON manifest carrying the
format version, the feature-schema version, tower dims, a sha256 of the npz
bytes and a content hash of the params. `load_checkpoint` REJECTS (raises
CheckpointError) on any mismatch — the caller keeps its previous policy, a
bad artifact can never be half-loaded.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional

import numpy as np

from yunikorn_tpu.policy.features import F_NODE, F_POD, FEATURE_VERSION

CKPT_FORMAT = 1
HIDDEN = 32
EMB = 16

# learned proposal-override gate: the chosen node's raw learned score must
# beat the mean over the pod's feasible nodes by this margin (shift-invariant
# — CE training is invariant to per-pod logit shifts) before the policy may
# override the water-fill proposal. Untrained nets score identically 0
# everywhere, so the gate can never fire.
GATE_MARGIN = 0.05


class CheckpointError(RuntimeError):
    """The checkpoint failed validation (corrupt payload, format/feature
    schema mismatch, shape drift). The previous policy must be retained."""


# ---------------------------------------------------------------------- net
def init_params(seed: int = 0, hidden: int = HIDDEN, emb: int = EMB) -> Dict:
    """Plain-pytree params. Hidden layers get small random init (seeded,
    reproducible); the POD tower's output layer is exactly zero so the
    untrained score matrix is exactly zero (see module docstring)."""
    rng = np.random.RandomState(seed)

    def lin(fin, fout, scale):
        return (np.asarray(rng.standard_normal((fin, fout)) * scale,
                           np.float32),
                np.zeros((fout,), np.float32))

    zero_out = (np.zeros((hidden, emb), np.float32),
                np.zeros((emb,), np.float32))
    return {
        "pod": (lin(F_POD, hidden, 1.0 / np.sqrt(F_POD)), zero_out),
        "node": (lin(F_NODE, hidden, 1.0 / np.sqrt(F_NODE)),
                 lin(hidden, emb, 1.0 / np.sqrt(hidden))),
        # gumbel exploration temperature of the proposal override (spreads
        # proposals across equally-scored nodes instead of herding onto the
        # lowest row index; ops/assign._learned_chunk_pass)
        "tau": np.float32(0.25),
    }


def _tower(layers, x):
    import jax.numpy as jnp

    (w1, b1), (w2, b2) = layers
    h = jnp.tanh(x @ w1 + b1)
    return h @ w2 + b2


def pod_tower(params, pod_feats):
    """[N, F_POD] -> [N, E]."""
    return _tower(params["pod"], pod_feats)


def node_tower(params, node_feats):
    """[M, F_NODE] -> [M, E]."""
    return _tower(params["node"], node_feats)


def score_matrix(params, pod_feats, node_feats):
    """[N, M] learned score (higher = prefer). Inference composes the same
    two calls inside the solve's chunked stages; this form is the trainer's
    and the tests'."""
    return pod_tower(params, pod_feats) @ node_tower(params, node_feats).T


# ------------------------------------------------------------- checkpoint IO
_LEAF_ORDER = ("pod_0_w", "pod_0_b", "pod_1_w", "pod_1_b",
               "node_0_w", "node_0_b", "node_1_w", "node_1_b", "tau")


def _flatten(params: Dict) -> Dict[str, np.ndarray]:
    (pw1, pb1), (pw2, pb2) = params["pod"]
    (nw1, nb1), (nw2, nb2) = params["node"]
    vals = (pw1, pb1, pw2, pb2, nw1, nb1, nw2, nb2, params["tau"])
    return {k: np.asarray(v, np.float32) for k, v in zip(_LEAF_ORDER, vals)}


def _unflatten(leaves: Dict[str, np.ndarray]) -> Dict:
    return {
        "pod": ((leaves["pod_0_w"], leaves["pod_0_b"]),
                (leaves["pod_1_w"], leaves["pod_1_b"])),
        "node": ((leaves["node_0_w"], leaves["node_0_b"]),
                 (leaves["node_1_w"], leaves["node_1_b"])),
        "tau": np.float32(leaves["tau"]),
    }


def params_hash(params: Dict) -> str:
    """Content hash of the params (16 hex chars): folds into the AOT
    fingerprint `extra` so a checkpoint swap can never serve a stale
    compiled executable, and into the policy_checkpoint_epoch gauge."""
    h = hashlib.sha256()
    for k, v in sorted(_flatten(params).items()):
        h.update(k.encode())
        h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()[:16]


@dataclasses.dataclass
class PolicyCheckpoint:
    params: Dict
    hash: str
    epoch: int
    manifest: dict
    prefix: str = ""


def save_checkpoint(prefix: str, params: Dict, *, epoch: int = 0,
                    meta: Optional[dict] = None) -> PolicyCheckpoint:
    """Write `<prefix>.npz` + `<prefix>.json` atomically (tmp + replace).
    Returns the checkpoint as the loader would see it."""
    leaves = _flatten(params)
    npz_path, man_path = prefix + ".npz", prefix + ".json"
    d = os.path.dirname(os.path.abspath(npz_path))
    os.makedirs(d, exist_ok=True)
    tmp = npz_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **leaves)
    with open(tmp, "rb") as f:
        npz_sha = hashlib.sha256(f.read()).hexdigest()
    os.replace(tmp, npz_path)
    phash = params_hash(params)
    manifest = {
        "format": CKPT_FORMAT,
        "feature_version": FEATURE_VERSION,
        "f_pod": F_POD,
        "f_node": F_NODE,
        "hidden": int(leaves["pod_0_w"].shape[1]),
        "emb": int(leaves["pod_1_w"].shape[1]),
        "epoch": int(epoch),
        "param_hash": phash,
        "npz_sha256": npz_sha,
        "leaves": {k: [list(v.shape), str(v.dtype)]
                   for k, v in leaves.items()},
        "meta": meta or {},
    }
    tmp = man_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, man_path)
    return PolicyCheckpoint(params=params, hash=phash, epoch=int(epoch),
                            manifest=manifest, prefix=prefix)


def load_checkpoint(prefix: str) -> PolicyCheckpoint:
    """Load + VALIDATE `<prefix>.npz` / `<prefix>.json`. Any failure raises
    CheckpointError with the specific reason; nothing is partially applied."""
    npz_path, man_path = prefix + ".npz", prefix + ".json"
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except Exception as e:
        raise CheckpointError(f"manifest unreadable at {man_path}: "
                              f"{type(e).__name__}: {e}")
    if manifest.get("format") != CKPT_FORMAT:
        raise CheckpointError(
            f"checkpoint format {manifest.get('format')!r} != supported "
            f"{CKPT_FORMAT}")
    if manifest.get("feature_version") != FEATURE_VERSION:
        raise CheckpointError(
            f"feature schema v{manifest.get('feature_version')} != the "
            f"extractor's v{FEATURE_VERSION} — retrain against the current "
            "features")
    if (manifest.get("f_pod"), manifest.get("f_node")) != (F_POD, F_NODE):
        raise CheckpointError("feature width mismatch "
                              f"({manifest.get('f_pod')}x"
                              f"{manifest.get('f_node')} != {F_POD}x{F_NODE})")
    try:
        with open(npz_path, "rb") as f:
            raw = f.read()
    except Exception as e:
        raise CheckpointError(f"params unreadable at {npz_path}: "
                              f"{type(e).__name__}: {e}")
    npz_sha = hashlib.sha256(raw).hexdigest()
    if npz_sha != manifest.get("npz_sha256"):
        raise CheckpointError("params payload sha256 mismatch (corrupt or "
                              "tampered npz)")
    import io

    try:
        with np.load(io.BytesIO(raw)) as z:
            leaves = {k: np.asarray(z[k], np.float32) for k in _LEAF_ORDER}
    except Exception as e:
        raise CheckpointError(f"params npz undecodable: "
                              f"{type(e).__name__}: {e}")
    want = manifest.get("leaves") or {}
    for k, v in leaves.items():
        spec = want.get(k)
        if spec is None or list(v.shape) != list(spec[0]):
            raise CheckpointError(
                f"leaf {k} shape {list(v.shape)} != manifest {spec}")
    if leaves["pod_0_w"].shape != (F_POD, manifest["hidden"]) \
            or leaves["node_0_w"].shape != (F_NODE, manifest["hidden"]) \
            or leaves["pod_1_w"].shape[1] != leaves["node_1_w"].shape[1]:
        raise CheckpointError("tower dims inconsistent with the feature "
                              "schema / embedding width")
    params = _unflatten(leaves)
    phash = params_hash(params)
    if phash != manifest.get("param_hash"):
        raise CheckpointError("param content hash mismatch "
                              f"({phash} != {manifest.get('param_hash')})")
    return PolicyCheckpoint(params=params, hash=phash,
                            epoch=int(manifest.get("epoch", 0)),
                            manifest=manifest, prefix=prefix)
