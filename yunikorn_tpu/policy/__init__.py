"""Learned dispatch policy (round 17, solver.policy=learned).

A small pure-JAX two-tower scorer trained OFFLINE from replay traces
(DOPPLER-style dual-policy learning, arXiv 2505.23131) and served INSIDE the
jitted assignment solve as a score-matrix augmentation + gated proposal
override — behind the round-12 differential oracle, so a bad checkpoint is a
measured no-op rather than an incident.

Modules:
  features   jitted fixed-shape feature extractor over the existing solve
             args ([N, F_POD] pod rows, [M, F_NODE] node rows) — every
             compiled learned variant stays a standard bucket
  net        the two-tower MLP (plain pytree params, flax-free), plus the
             versioned checkpoint format (.npz + JSON manifest) with
             REJECT-on-mismatch validation
  train      dataset IO (the trace-replay --dataset-out format) and the
             offline trainer: imitation of recorded choose_plan duel
             winners, then fine-tuning on a packed-units + contention
             objective
"""
