"""Offline DOPPLER-style training from replay datasets.

Training data is the per-cycle duel record the scheduler itself produces
(`CoreScheduler.policy_recorder`, wired by `scripts/trace_replay.py
--dataset-out` and `scripts/policy_bench.py`): the RAW solve tensors of one
cycle — quantized request rows, round-0 free capacity, node capacities and
validity, priorities — plus every candidate plan that entered the
`choose_plan` duel and the duel's winner. Recording raw tensors (not
features) keeps datasets valid across feature-schema bumps: the trainer
derives features with the SAME `policy/features.py` functions inference
uses, so train/serve skew is structurally impossible.

Two phases (train.fit):

  imitation   cross-entropy of the scorer's per-pod node distribution
              against the recorded duel WINNER's assignment, masked to
              fit-feasible nodes — the policy first learns to reproduce
              whichever plan the differential oracle actually committed
              (greedy on homogeneous cycles, the LP pack plan exactly on
              the fragmented cycles where a global view pays).
  fine-tune   a differentiable relaxation of the packing objective itself:
              soft-assign each ask across its feasible nodes (softmax with
              an always-available null column, the pack LP's drop-out
              semantics), maximize expected capacity-normalized placed
              units minus per-node-per-resource overload and a mild
              contention penalty on busy nodes. This is the dual-policy
              refinement step: the scorer stops imitating and starts
              optimizing the committed objective directly.

Feasibility in the dataset is FIT feasibility (free >= request, node
schedulable): the proving-ground traces carry no selector constraints, and
the solver re-checks full group feasibility at inference anyway — an
over-permissive training mask can only cost score quality, never
correctness (the differential oracle is the floor).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from yunikorn_tpu.log.logger import log
from yunikorn_tpu.policy import features as pf
from yunikorn_tpu.policy import net as pnet

logger = log("policy.train")

_MASK = -1.0e9


# ---------------------------------------------------------------- dataset IO
_ARRAY_KEYS = ("req", "rank", "valid", "free0", "cap", "node_ok",
               "priorities")


class DatasetWriter:
    """Append per-cycle duel examples as one .npz each + a manifest.jsonl.
    Bounded (max_cycles) so a long replay cannot fill the disk; callable so
    it plugs straight into CoreScheduler.policy_recorder."""

    def __init__(self, path: str, max_cycles: int = 512,
                 fresh: bool = True):
        self.path = path
        self.max_cycles = int(max_cycles)
        self.written = 0
        self.dropped = 0
        os.makedirs(path, exist_ok=True)
        if fresh:
            # a writer owns its dataset dir: stale cycles from a previous
            # run (or a previous --ab arm on the same path) would silently
            # mix into training via load_dataset's glob
            for name in os.listdir(path):
                if ((name.startswith("cycle_") and name.endswith(".npz"))
                        or name == "manifest.jsonl"):
                    os.unlink(os.path.join(path, name))

    def write(self, example: Dict) -> bool:
        if self.written >= self.max_cycles:
            self.dropped += 1
            return False
        out = {k: np.asarray(example[k]) for k in _ARRAY_KEYS
               if k in example}
        for k, v in example.items():
            if k.startswith("plan_") and v is not None:
                out[k] = np.asarray(v, np.int32)
        out["score_cols"] = np.asarray(int(example["score_cols"]))
        out["winner"] = np.asarray(str(example.get("winner", "greedy")))
        fname = f"cycle_{self.written:05d}.npz"
        fp = os.path.join(self.path, fname)
        with open(fp + ".tmp", "wb") as f:
            np.savez_compressed(f, **out)
        os.replace(fp + ".tmp", fp)
        with open(os.path.join(self.path, "manifest.jsonl"), "a") as f:
            f.write(json.dumps({
                "file": fname, "winner": str(out["winner"]),
                "pods": int(out["req"].shape[0]),
                "nodes": int(out["free0"].shape[0]),
                "plans": sorted(k for k in out if k.startswith("plan_")),
            }) + "\n")
        self.written += 1
        return True

    __call__ = write


def load_dataset(path: str) -> List[Dict]:
    """Read every cycle npz under `path` (sorted, deterministic)."""
    out = []
    for name in sorted(os.listdir(path)):
        if not (name.startswith("cycle_") and name.endswith(".npz")):
            continue
        with np.load(os.path.join(path, name)) as z:
            ex = {k: np.asarray(z[k]) for k in z.files}
        ex["score_cols"] = int(ex["score_cols"])
        ex["winner"] = str(ex["winner"])
        out.append(ex)
    return out


# ------------------------------------------------------------------ trainer
def _prepare(ex: Dict) -> Optional[Dict]:
    """Derive the fixed-shape training tensors for one recorded cycle."""
    sc = int(ex["score_cols"])
    req = np.asarray(ex["req"], np.int32)
    free0 = np.asarray(ex["free0"], np.int32)
    cap = np.asarray(ex["cap"], np.int32)
    valid = np.asarray(ex["valid"], bool)
    node_ok = np.asarray(ex["node_ok"], bool)
    n, r = req.shape
    m = free0.shape[0]
    sc = min(max(sc, 1), r)
    winner = ex.get("winner", "greedy")
    target = ex.get(f"plan_{winner}", ex.get("plan_greedy"))
    if target is None or n == 0 or m == 0:
        return None
    # plans are recorded over the LIVE asks ([:num_pods]) while the solve
    # tensors keep their bucket padding — pad with -1 (padded rows are
    # valid=False and masked out of every loss)
    target = np.asarray(target, np.int32)
    if target.shape[0] < n:
        target = np.concatenate(
            [target, np.full(n - target.shape[0], -1, np.int32)])
    target = target[:n]
    # fit feasibility over ALL recorded columns (ports ride synthetic
    # columns in req/free0 when present) — loop keeps memory at [N, M]
    ok = np.broadcast_to(valid[:, None] & node_ok[None, :], (n, m)).copy()
    for col in range(r):
        ok &= (free0[None, :, col] - req[:, None, col]) >= 0
    inv = np.asarray(pf.inv_capacity_scale(cap[:, :sc]))
    pod_f = np.asarray(pf.pod_features(req[:, :sc], inv))
    node_f = np.asarray(pf.node_features(free0[:, :sc], cap[:, :sc], inv))
    q = req[:, :sc].astype(np.float64) * inv[None, :]
    return {
        "pod_f": pod_f.astype(np.float32),
        "node_f": node_f.astype(np.float32),
        "ok": ok,
        "target": target,
        "tmask": valid & (target >= 0),
        "valid_rows": valid.astype(np.float32),
        "vunits": q.sum(axis=1).astype(np.float32),
        "req_n": q.astype(np.float32),
        "free_n": (np.clip(free0[:, :sc], 0, None).astype(np.float64)
                   * inv[None, :]).astype(np.float32),
        # contention proxy: how busy the node already is (BandPilot's
        # co-tenant pressure signal, absent per-domain labels)
        "cont": (1.0 - node_f[:, pf.FEAT_COLS]).astype(np.float32),
    }


def _adam_init(params):
    import jax

    z = jax.tree_util.tree_map(lambda a: np.zeros_like(np.asarray(a)), params)
    return z, jax.tree_util.tree_map(np.copy, z)


def _adam_step(params, grads, m, v, t, lr, b1=0.9, b2=0.999, eps=1e-8):
    import jax

    def upd(p, g, mi, vi):
        mi = b1 * mi + (1 - b1) * g
        vi = b2 * vi + (1 - b2) * g * g
        mhat = mi / (1 - b1 ** t)
        vhat = vi / (1 - b2 ** t)
        return p - lr * mhat / (np.sqrt(vhat) + eps), mi, vi

    flat_p, tree = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_flatten(grads)[0]
    flat_m = jax.tree_util.tree_flatten(m)[0]
    flat_v = jax.tree_util.tree_flatten(v)[0]
    out_p, out_m, out_v = [], [], []
    for p, g, mi, vi in zip(flat_p, flat_g, flat_m, flat_v):
        p2, m2, v2 = upd(np.asarray(p, np.float32), np.asarray(g, np.float32),
                         mi, vi)
        out_p.append(p2)
        out_m.append(m2)
        out_v.append(v2)
    unf = jax.tree_util.tree_unflatten
    return unf(tree, out_p), unf(tree, out_m), unf(tree, out_v)


def fit(examples: List[Dict], *, seed: int = 0, imitation_epochs: int = 80,
        finetune_epochs: int = 60, lr: float = 5e-3, beta: float = 4.0,
        overload_w: float = 2.0, contention_w: float = 0.05,
        ) -> Tuple[Dict, Dict]:
    """Train a scorer from recorded duel cycles. Returns (params, report).
    Deterministic in (examples, seed, hyperparameters)."""
    import jax
    import jax.numpy as jnp

    preps = [p for p in (_prepare(ex) for ex in examples) if p is not None]
    if not preps:
        raise ValueError("dataset contains no trainable cycles")

    def im_loss(params, pod_f, node_f, ok, target, tmask):
        ls = pnet.score_matrix(params, pod_f, node_f)
        logits = jnp.where(ok, ls, _MASK)
        lse = jax.scipy.special.logsumexp(logits, axis=1)
        m = logits.shape[1]
        tgt = jnp.take_along_axis(
            logits, jnp.clip(target, 0, m - 1)[:, None], axis=1)[:, 0]
        ce = jnp.where(tmask, lse - tgt, 0.0)
        return jnp.sum(ce) / jnp.maximum(jnp.sum(tmask), 1)

    def ft_loss(params, pod_f, node_f, ok, vunits, req_n, free_n, cont,
                valid_rows):
        ls = pnet.score_matrix(params, pod_f, node_f)
        n, m = ls.shape
        logits = jnp.where(ok, beta * ls, _MASK)
        aug = jnp.concatenate([logits, jnp.zeros((n, 1), jnp.float32)],
                              axis=1)
        p = jax.nn.softmax(aug, axis=1)[:, :m]
        p = jnp.where(ok, p, 0.0) * valid_rows[:, None]
        placed = jnp.sum(p, axis=1)
        units = jnp.sum(vunits * placed)
        load = p.T @ req_n                                    # [M, sc]
        over = jnp.sum(jnp.maximum(load - free_n, 0.0))
        # contention is a per-pod DISCOUNT on the units earned on busy
        # nodes (weighting by vunits keeps it a fraction of the packing
        # objective — an absolute penalty would swamp small-pod cycles)
        contention = jnp.sum((vunits[:, None] * p) * cont[None, :])
        n_eff = jnp.maximum(jnp.sum(valid_rows), 1.0)
        return -(units - overload_w * over
                 - contention_w * contention) / n_eff

    im_grad = jax.jit(jax.value_and_grad(im_loss))
    ft_grad = jax.jit(jax.value_and_grad(ft_loss))

    params = jax.tree_util.tree_map(lambda a: np.asarray(a, np.float32),
                                    pnet.init_params(seed))
    m_s, v_s = _adam_init(params)
    t = 0
    report = {"examples": len(preps), "imitation": [], "finetune": []}
    for epoch in range(imitation_epochs):
        tot = 0.0
        for p in preps:
            t += 1
            loss, g = im_grad(params, p["pod_f"], p["node_f"], p["ok"],
                              p["target"], p["tmask"])
            params, m_s, v_s = _adam_step(params, g, m_s, v_s, t, lr)
            tot += float(loss)
        if epoch in (0, imitation_epochs - 1) or epoch % 20 == 0:
            report["imitation"].append(round(tot / len(preps), 5))
    for epoch in range(finetune_epochs):
        tot = 0.0
        for p in preps:
            t += 1
            loss, g = ft_grad(params, p["pod_f"], p["node_f"], p["ok"],
                              p["vunits"], p["req_n"], p["free_n"],
                              p["cont"], p["valid_rows"])
            params, m_s, v_s = _adam_step(params, g, m_s, v_s, t, lr * 0.5)
            tot += float(loss)
        if epoch in (0, finetune_epochs - 1) or epoch % 20 == 0:
            report["finetune"].append(round(tot / len(preps), 5))
    return params, report
