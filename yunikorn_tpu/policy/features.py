"""Jitted feature extraction over the solver's existing arguments.

The learned scorer must compose with the solver's standard-bucket compile
discipline (docs/PERF.md: unbounded shapes mean unbounded compiles), so the
feature tensors are FIXED-WIDTH regardless of the fleet's resource-vocab
width R: per-pod rows are [N, F_POD] and per-node rows are [M, F_NODE], with
the first FEAT_COLS resource columns carried verbatim (zero-padded when the
vocab is narrower) and the rest summarized as scale-free aggregates. Every
value is normalized — per-column by the fleet's mean node capacity (the same
inv_scale the pack LP prices with) or per-node by the node's own capacity —
so a checkpoint trained at one fleet scale transfers to another.

FEATURE_VERSION is part of the checkpoint manifest: a checkpoint trained
against a different feature schema REJECTS at load (net.load_checkpoint)
instead of silently scoring garbage.

All functions here are pure jnp and trace inside the solver's jitted
programs; the trainer calls the same functions on host arrays, so the
features seen at train time and at inference time cannot drift.
"""
from __future__ import annotations

import jax.numpy as jnp

# bump when the shape OR semantics of any feature column changes — the
# checkpoint loader rejects manifests built against a different version
FEATURE_VERSION = 1

# resource columns carried verbatim (zero-padded); the common fleets carry
# 2-4 real columns (cpu, memory, extended resources)
FEAT_COLS = 4

F_POD = 8
F_NODE = 8


def inv_capacity_scale(cap_i) -> jnp.ndarray:
    """[R] per-column normalization: 1 / mean node capacity — the scale the
    pack LP and packed_utilization already normalize with, so the learned
    objective and the duel's objective agree on what a "unit" is."""
    return 1.0 / jnp.maximum(
        jnp.mean(cap_i.astype(jnp.float32), axis=0), 1.0)


def _first_cols(x, width: int):
    """[*, R] -> [*, width]: verbatim leading columns, zero-padded (static
    Python on the trace-time column count, so no dynamic shapes)."""
    r = x.shape[1]
    if r >= width:
        return x[:, :width]
    pad = jnp.zeros((x.shape[0], width - r), x.dtype)
    return jnp.concatenate([x, pad], axis=1)


def pod_features(req_i, inv_scale) -> jnp.ndarray:
    """[N, F_POD] per-ask features from the quantized request rows.

    req_i: [N, R] int32 requests over the SCORING columns only (the caller
    slices off synthetic port columns); inv_scale: [R] from
    inv_capacity_scale. Columns:
      0..3  normalized request, first FEAT_COLS columns verbatim
      4     total normalized request (the ask's "size" in solver units)
      5     max normalized column (the bottleneck resource)
      6     dominant share: max / total (1.0 = single-resource ask — the
            shape signal the alignment policy keys on)
      7     breadth: fraction of scoring columns the ask requests
    """
    q = req_i.astype(jnp.float32) * inv_scale[None, :]          # [N, R]
    total = jnp.sum(q, axis=1)
    mx = jnp.max(q, axis=1) if q.shape[1] else jnp.zeros_like(total)
    dom = mx / jnp.maximum(total, 1e-9)
    breadth = (jnp.sum((q > 0).astype(jnp.float32), axis=1)
               / float(max(q.shape[1], 1)))
    return jnp.concatenate(
        [_first_cols(q, FEAT_COLS),
         total[:, None], mx[:, None], dom[:, None], breadth[:, None]],
        axis=1)


def node_features(free_i, cap_i, inv_scale) -> jnp.ndarray:
    """[M, F_NODE] per-node features from CURRENT free capacity — the round
    loop recomputes these as placements land, exactly like the base score.

    free_i/cap_i: [M, R] int32 over the scoring columns. The verbatim
    columns are FLEET-normalized absolute free (free * inv_scale), not
    own-capacity fractions: two heterogeneous flavors that are both empty
    have identical fractions everywhere, and a scorer fed only fractions
    provably cannot tell a cpu-rich node from a mem-rich one on the
    fragmented shapes where shape-aware placement pays (the round-17
    training-signal finding). Columns:
      0..3  fleet-normalized free, first FEAT_COLS columns verbatim (the
            per-resource headroom SHAPE — the alignment signal)
      4     mean free fraction of own capacity (1 - binpacking base score)
      5     min free fraction (the node's bottleneck)
      6     max free fraction (the node's slack shape)
      7     MEAN fleet-normalized free across the scoring columns (the
            absolute-headroom scale signal — a big empty node scores
            higher than a small empty one; mean not sum, so the value
            stays comparable across vocab widths). This column's code IS
            the versioned contract — changing its arithmetic requires a
            FEATURE_VERSION bump.
    """
    cap = jnp.maximum(cap_i.astype(jnp.float32), 1.0)
    pos = jnp.clip(free_i.astype(jnp.float32), 0.0, None)
    q = pos * inv_scale[None, :]                                # [M, R]
    f = pos / cap                                               # [M, R]
    mean_f = jnp.mean(f, axis=1)
    min_f = jnp.min(f, axis=1) if f.shape[1] else jnp.zeros_like(mean_f)
    max_f = jnp.max(f, axis=1) if f.shape[1] else jnp.zeros_like(mean_f)
    total = jnp.sum(q, axis=1) / float(max(free_i.shape[1], 1))
    return jnp.concatenate(
        [_first_cols(q, FEAT_COLS),
         mean_f[:, None], min_f[:, None], max_f[:, None], total[:, None]],
        axis=1)
