"""Deadlock-detecting lock wrappers.

Role-equivalent to the reference's pkg/locking/locking.go:38-44, which wraps every
mutex in the codebase with `sasha-s/go-deadlock` and toggles detection via the
DEADLOCK_DETECTION_ENABLED / DEADLOCK_TIMEOUT_SECONDS / DEADLOCK_EXIT env vars
(reference Makefile:586-589). Here, when detection is enabled, acquisitions use a
timeout; on timeout the holder's stack is dumped and a DeadlockError is raised
(or the process aborted when DEADLOCK_EXIT is set).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Optional


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


DETECTION_ENABLED = _env_bool("DEADLOCK_DETECTION_ENABLED")
TIMEOUT_SECONDS = float(os.environ.get("DEADLOCK_TIMEOUT_SECONDS", "60"))
EXIT_ON_DEADLOCK = _env_bool("DEADLOCK_EXIT")


class DeadlockError(RuntimeError):
    pass


def _on_timeout(kind: str, holder_info: str) -> None:
    msg = f"POTENTIAL DEADLOCK: failed to acquire {kind} within {TIMEOUT_SECONDS}s\n{holder_info}"
    frames = []
    for tid, frame in sys._current_frames().items():
        frames.append(f"--- thread {tid} ---\n" + "".join(traceback.format_stack(frame)))
    msg += "\n" + "\n".join(frames)
    if EXIT_ON_DEADLOCK:
        print(msg, file=sys.stderr)
        os._exit(2)
    raise DeadlockError(msg)


class Mutex:
    """Reentrancy-free mutex with optional deadlock detection."""

    def __init__(self):
        self._lock = threading.Lock()
        self._holder: Optional[str] = None

    def acquire(self) -> None:
        if DETECTION_ENABLED:
            if not self._lock.acquire(timeout=TIMEOUT_SECONDS):
                _on_timeout("Mutex", f"held by: {self._holder}")
            # holder tracking is diagnostic-only; current_thread() per
            # acquisition is measurable on the pump's hot path, so production
            # (detection off) skips it
            self._holder = threading.current_thread().name
        else:
            self._lock.acquire()

    def release(self) -> None:
        if DETECTION_ENABLED:
            self._holder = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class RMutex(Mutex):
    """Reentrant mutex (threading.RLock analog) with optional detection.

    The reference wraps every mutex in the codebase (locking.go:38-44);
    components whose call graphs re-enter their own lock use this variant.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._holder: Optional[str] = None

    def release(self) -> None:
        # unlike Mutex, keep _holder: under nesting the outer frames still
        # hold the lock; the name is diagnostic only either way
        self._lock.release()


class RWMutex:
    """Reader-writer lock with optional deadlock detection.

    Matches the usage pattern of the reference's locking.RWMutex: many informer /
    dispatcher threads take RLock, state mutation takes Lock.

    Fast path (detection OFF, the production default): a single reentrant
    lock for both sides. Under the GIL, pure-Python critical sections never
    actually read in parallel, so the Condition-based writer-preferring
    implementation buys nothing while costing ~µs per acquisition and
    serializing readers behind writer pressure — profiled as the dominant
    term of the 50k-pod shim benchmark (1.9M acquisitions). The RLock is
    also strictly more permissive (reader-inside-writer nesting works).
    Detection ON keeps the instrumented reader/writer implementation.
    """

    def __init__(self):
        if not DETECTION_ENABLED:
            self._rlock = threading.RLock()
            return
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    # -- write side --
    def acquire(self) -> None:
        if not DETECTION_ENABLED:
            self._rlock.acquire()
            return
        with self._cond:
            self._writers_waiting += 1
            try:
                if not self._cond.wait_for(
                    lambda: not self._writer and self._readers == 0,
                    timeout=TIMEOUT_SECONDS,
                ):
                    _on_timeout("RWMutex(write)", f"readers={self._readers} writer={self._writer}")
                self._writer = True
            finally:
                self._writers_waiting -= 1

    def release(self) -> None:
        if not DETECTION_ENABLED:
            self._rlock.release()
            return
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    # -- read side --
    def r_acquire(self) -> None:
        if not DETECTION_ENABLED:
            self._rlock.acquire()
            return
        with self._cond:
            if not self._cond.wait_for(
                lambda: not self._writer and self._writers_waiting == 0,
                timeout=TIMEOUT_SECONDS,
            ):
                _on_timeout("RWMutex(read)", f"writer held={self._writer}")
            self._readers += 1

    def r_release(self) -> None:
        if not DETECTION_ENABLED:
            self._rlock.release()
            return
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    class _ReadGuard:
        __slots__ = ("_rw",)

        def __init__(self, rw: "RWMutex"):
            self._rw = rw

        def __enter__(self):
            self._rw.r_acquire()
            return self

        def __exit__(self, *exc):
            self._rw.r_release()
            return False

    def reader(self) -> "RWMutex._ReadGuard":
        return RWMutex._ReadGuard(self)
