"""REST API: the scheduler's /ws/v1/* surface.

The reference's REST endpoints live in yunikorn-core (the E2E harness drives
them through `RClient`, reference test/e2e/framework/helpers/yunikorn/
rest_api_utils.go: queues, apps, nodes, health, full state dump, validate-conf)
and the shim contributes its cache DAO to the state dump (context.go:1348-1360).
This server exposes the same paths over the in-process core + shim context.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from yunikorn_tpu.log.logger import log

logger = log("core")


class RestServer:
    def __init__(self, core, context=None, host: str = "127.0.0.1", port: int = 9080):
        self.core = core
        self.context = context
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        core, context = self.core, self.context

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("rest: " + fmt, *args)

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/")
                dao = core.get_partition_dao()
                if path in ("/ws/v1/health", "/health"):
                    self._reply(200, {"Healthy": True})
                elif path in ("/ws/v1/queues", "/ws/v1/partition/default/queues"):
                    self._reply(200, dao["queues"])
                elif path in ("/ws/v1/apps", "/ws/v1/partition/default/applications"):
                    self._reply(200, dao["partition"]["applications"])
                elif path in ("/ws/v1/nodes", "/ws/v1/partition/default/nodes"):
                    self._reply(200, dao["partition"]["nodes"])
                elif path == "/ws/v1/metrics":
                    self._reply(200, dao["metrics"])
                elif path == "/ws/v1/fullstatedump":
                    dump = {"core": dao}
                    if context is not None:
                        dump["shim"] = context.state_dump()
                    self._reply(200, dump)
                else:
                    self._reply(404, {"error": f"unknown path {path}"})

            def do_POST(self):
                parsed = urlparse(self.path)
                if parsed.path.rstrip("/") == "/ws/v1/validate-conf":
                    length = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(length).decode()
                    ok, message = core.validate_configuration(body)
                    self._reply(200, {"allowed": ok, "reason": message})
                else:
                    self._reply(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="rest-api", daemon=True)
        self._thread.start()
        logger.info("REST API serving on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
