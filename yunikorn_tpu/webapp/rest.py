"""REST API: the scheduler's /ws/v1/* surface.

The reference's REST endpoints live in yunikorn-core (the E2E harness drives
them through `RClient`, reference test/e2e/framework/helpers/yunikorn/
rest_api_utils.go: queues, apps, nodes, health, full state dump, validate-conf)
and the shim contributes its cache DAO to the state dump (context.go:1348-1360).
This server exposes the same paths over the in-process core + shim context.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from yunikorn_tpu.log.logger import log

logger = log("core")


def _usage_dao(core, partition: str, kind: str) -> list:
    """Per-user / per-group resource trackers (reference RClient usage APIs:
    /ws/v1/partition/{p}/usage/users|groups over yunikorn-core's ugm): walk
    the partition's queue tree and report each tracked user/group's allocated
    resources and running application count per queue."""
    tree = core.queue_trees.get(partition)
    if tree is None:
        return []
    out: dict = {}

    def walk(q):
        alloc_map = q.user_allocated if kind == "users" else q.group_allocated
        count_map = q.user_app_counts if kind == "users" else q.group_app_counts
        for name, res in alloc_map.items():
            entry = out.setdefault(name, {"name": name, "queues": {}})
            entry["queues"][q.full_name] = {
                "resourceUsage": dict(res.resources),
                "runningApplications": count_map.get(name, 0),
            }
        for child in q.children.values():
            walk(child)

    # the scheduler thread mutates these maps under the core lock; every
    # other endpoint reads through get_partition_dao() which locks too
    with core._lock:
        walk(tree.root)
    return sorted(out.values(), key=lambda e: e["name"])


# NOTE: the old `_prometheus_text` flattener (counter-vs-gauge guessed from
# name suffixes) is gone — both metrics surfaces now render from the SAME
# declared registry (core.obs): `/metrics` via MetricsRegistry.expose()
# (correct # TYPE lines, histogram _bucket/_sum/_count series, label
# escaping) and `/ws/v1/metrics` via core.metrics_snapshot() (the JSON view
# of the identical families, plus the per-partition last_cycle breakdown).


class RestServer:
    def __init__(self, core, context=None, host: str = "127.0.0.1", port: int = 9080):
        self.core = core
        self.context = context
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        core, context = self.core, self.context

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("rest: " + fmt, *args)

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/")

                # hot endpoints first: /health (probes), /metrics (Prometheus
                # scrapes every few seconds) and /debug/traces must not build
                # the full partition DAO — serializing 10k nodes under the
                # core lock per scrape would stall scheduling cycles
                if path in ("/ws/v1/health", "/health"):
                    # real liveness/readiness with per-component detail
                    # (robustness/health.py): circuit/degradation state,
                    # last-cycle failures, informer staleness, dispatcher
                    # backlog. 503 on liveness failure so a plain HTTP
                    # probe restarts a dead loop; a DEGRADED scheduler is
                    # serving and stays 200 (detail says how).
                    if hasattr(core, "health_report"):
                        report = core.health_report()
                    else:
                        report = {"Healthy": True}
                    return self._reply(
                        200 if report.get("Healthy", True) else 503, report)
                if path == "/metrics":
                    body = core.obs.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path in ("/debug/traces", "/ws/v1/traces", "/ws/v1/trace"):
                    # Chrome trace-event JSON of the ring-buffered cycle
                    # spans (open in Perfetto / chrome://tracing): the
                    # pipelined overlap renders as parallel lanes. On the
                    # sharded scheduler, core.tracer is the FleetTracer —
                    # one merged trace, one pid per shard + a front lane
                    return self._reply(200, core.tracer.chrome_trace())
                if path.startswith("/ws/v1/journey/"):
                    # per-pod journey record: hop timeline, stage durations
                    # (their sum tiles the e2e latency exactly), outcome
                    if not hasattr(core, "journey"):
                        return self._reply(404, {"error": "journey ledger "
                                                          "unavailable"})
                    uid = parsed.path[len("/ws/v1/journey/"):].strip("/")
                    rec = core.journey.get(uid)
                    if rec is None:
                        return self._reply(
                            404, {"error": f"no journey for {uid}"})
                    return self._reply(200, rec)
                if path == "/ws/v1/flightrec":
                    # flight-recorder state: bundles on disk + trigger stats
                    if not hasattr(core, "flightrec"):
                        return self._reply(404, {"error": "flight recorder "
                                                          "unavailable"})
                    return self._reply(200, {
                        "stats": core.flightrec.stats(),
                        "recordings": core.flightrec.list_recordings()})
                if path == "/ws/v1/metrics":
                    # same registry snapshot that backs /metrics, as JSON
                    return self._reply(200, core.metrics_snapshot())
                if path == "/ws/v1/slo":
                    # streaming SLO engine (obs/slo.py): per-objective
                    # verdict (ok | burning | violated), measured value vs
                    # target, and fast/slow-window burn rates — the same
                    # report the trace-replay proving ground gates on
                    if hasattr(core, "slo"):
                        return self._reply(200, core.slo.report())
                    return self._reply(404, {"error": "slo engine "
                                                      "unavailable"})
                if path == "/ws/v1/shards":
                    # control-plane sharding (core/shard.py): per-shard
                    # node/commit/cycle counts + async delivery-queue
                    # stats (depth/delivered/shed/dead per shard),
                    # repair-pass + quota-ledger + device-usage-mirror
                    # + partition-epoch state. 404 on the single-shard
                    # scheduler — the surface exists only when sharded
                    if hasattr(core, "shard_report"):
                        return self._reply(200, core.shard_report())
                    return self._reply(404, {"error": "scheduler is not "
                                                      "sharded"})
                if path == "/ws/v1/preemptions":
                    # recent preemption plans (ring-buffered): which ask
                    # evicted which victims on which node, by which planner
                    # (device = batched victim-selection solve, host =
                    # fallback loop)
                    return self._reply(200,
                                       {"Preemptions": core.recent_preemptions()})
                if path == "/ws/v1/events":
                    # filtered event tail (failure triage without a
                    # debugger): ?objectKey=ns/name&reason=R&count=N
                    from yunikorn_tpu.common.events import get_recorder

                    q = parse_qs(parsed.query)
                    try:
                        count = max(1, int(q.get("count", ["1000"])[0]))
                    except ValueError:
                        return self._reply(400, {"error": "invalid count"})
                    events = get_recorder().events(
                        object_key=q.get("objectKey", [None])[0],
                        reason=q.get("reason", [None])[0])[-count:]
                    return self._reply(200, {"EventRecords": [
                        {"objectKind": e.object_kind, "objectID": e.object_key,
                         "type": e.event_type, "reason": e.reason,
                         "message": e.message, "timestamp": e.timestamp}
                        for e in events]})

                dao = core.get_partition_dao()

                # /ws/v1/partition/{name}/{what...} — partition-parameterized
                # (reference RClient drives per-partition paths)
                parts = path.strip("/").split("/")
                if len(parts) >= 4 and parts[:3] == ["ws", "v1", "partition"]:
                    pname, what = parts[3], "/".join(parts[4:])
                    pd = dao.get("partitions", {}).get(pname) if pname != "default" else dao
                    if pd is None:
                        return self._reply(404, {"error": f"unknown partition {pname}"})
                    if what == "queues":
                        return self._reply(200, pd["queues"])
                    if what == "applications":
                        return self._reply(200, pd["partition"]["applications"])
                    if what == "nodes":
                        return self._reply(200, pd["partition"]["nodes"])
                    if what == "usage/users":
                        return self._reply(200, _usage_dao(core, pname, "users"))
                    if what == "usage/groups":
                        return self._reply(200, _usage_dao(core, pname, "groups"))
                    return self._reply(404, {"error": f"unknown path {path}"})

                if path == "/ws/v1/partitions":
                    with core._lock:
                        names = sorted(core.partitions)
                    self._reply(200, names)
                elif path == "/ws/v1/queues":
                    self._reply(200, dao["queues"])
                elif path == "/ws/v1/apps":
                    self._reply(200, dao["partition"]["applications"])
                elif path == "/ws/v1/nodes":
                    self._reply(200, dao["partition"]["nodes"])
                elif path == "/ws/v1/events/batch":
                    # K8s-event stream analog (reference RClient events API);
                    # ?count=N bounds the tail
                    from yunikorn_tpu.common.events import get_recorder

                    q = parse_qs(parsed.query)
                    try:
                        count = max(1, int(q.get("count", ["1000"])[0]))
                    except ValueError:
                        return self._reply(400, {"error": "invalid count"})
                    events = get_recorder().events()[-count:]
                    self._reply(200, {"EventRecords": [
                        {"objectKind": e.object_kind, "objectID": e.object_key,
                         "type": e.event_type, "reason": e.reason,
                         "message": e.message} for e in events]})
                elif path == "/ws/v1/fullstatedump":
                    dump = {"core": dao}
                    if context is not None:
                        dump["shim"] = context.state_dump()
                    self._reply(200, dump)
                else:
                    self._reply(404, {"error": f"unknown path {path}"})

            def do_POST(self):
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/")
                if path == "/ws/v1/validate-conf":
                    length = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(length).decode()
                    ok, message = core.validate_configuration(body)
                    self._reply(200, {"allowed": ok, "reason": message})
                elif path == "/ws/v1/profile/start":
                    # JAX profiler capture (SURVEY §5: the reference captures
                    # pprof in its perf test; the TPU analog is a profiler
                    # trace viewable in TensorBoard/XProf). ?name=<run> picks a
                    # subdirectory under the configured base — never an
                    # arbitrary client-chosen path.
                    import os
                    import re as _re

                    import jax

                    q = parse_qs(parsed.query)
                    name = q.get("name", ["trace"])[0]
                    # at least one alphanumeric: rejects "." / ".." aliases
                    if not _re.fullmatch(r"(?=.*[A-Za-z0-9])[A-Za-z0-9._-]{1,64}",
                                         name):
                        return self._reply(400, {"error": "invalid trace name"})
                    base = os.environ.get("YK_PROFILE_DIR", "/tmp/yk-profile")
                    trace_dir = os.path.join(base, name)
                    try:
                        jax.profiler.start_trace(trace_dir)
                        self._reply(200, {"tracing": True, "dir": trace_dir})
                    except Exception as e:
                        self._reply(409, {"error": str(e)})
                elif path == "/ws/v1/flightrec/dump":
                    # operator-triggered post-mortem bundle; bypasses the
                    # per-trigger debounce (an operator hitting dump wants
                    # a bundle NOW, not "one fired 10s ago")
                    if not hasattr(core, "flightrec"):
                        return self._reply(404, {"error": "flight recorder "
                                                          "unavailable"})
                    q = parse_qs(parsed.query)
                    reason = q.get("reason", ["operator dump"])[0]
                    p = core.flightrec.record("manual", reason=reason,
                                              force=True)
                    if p is None:
                        return self._reply(
                            409, {"error": "recorder disabled (no "
                                           "flightRecorderDir) or dump "
                                           "failed"})
                    self._reply(200, {"recorded": True, "path": p})
                elif path == "/ws/v1/profile/stop":
                    import jax

                    try:
                        jax.profiler.stop_trace()
                        self._reply(200, {"tracing": False})
                    except Exception as e:
                        self._reply(409, {"error": str(e)})
                else:
                    self._reply(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="rest-api", daemon=True)
        self._thread.start()
        logger.info("REST API serving on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
