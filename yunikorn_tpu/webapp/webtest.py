"""webtest server: static files + /ws/ reverse proxy.

Role-equivalent to pkg/cmd/webtest/main.go + pkg/webtest/web_server.go:46-60 —
a static-file server whose /ws/ paths reverse-proxy to the scheduler REST API;
only used as the web image for E2E tests (reference Makefile:550-561).

Usage:
    python -m yunikorn_tpu.webapp.webtest --root ./site --api http://127.0.0.1:9080
"""
from __future__ import annotations

import argparse
import functools
import sys
import threading
import urllib.request
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from yunikorn_tpu.log.logger import log

logger = log("shim.client")


class WebTestServer:
    def __init__(self, root: str, api_base: str, host: str = "127.0.0.1", port: int = 9889):
        self.root = root
        self.api_base = api_base.rstrip("/")
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread = None

    def start(self) -> int:
        api_base = self.api_base

        class Handler(SimpleHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("webtest: " + fmt, *args)

            def do_GET(self):
                if self.path.startswith("/ws/"):
                    try:
                        with urllib.request.urlopen(api_base + self.path, timeout=10) as resp:
                            body = resp.read()
                            self.send_response(resp.status)
                            self.send_header("Content-Type",
                                             resp.headers.get("Content-Type", "application/json"))
                            self.send_header("Content-Length", str(len(body)))
                            self.end_headers()
                            self.wfile.write(body)
                    except Exception as e:
                        self.send_error(502, f"proxy error: {e}")
                else:
                    super().do_GET()

        handler = functools.partial(Handler, directory=self.root)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="webtest", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="yunikorn-tpu webtest server")
    parser.add_argument("--root", type=str, default=".")
    parser.add_argument("--api", type=str, default="http://127.0.0.1:9080")
    parser.add_argument("--port", type=int, default=9889)
    parser.add_argument("--host", type=str, default="127.0.0.1",
                        help="bind address (0.0.0.0 in a container)")
    args = parser.parse_args(argv)
    server = WebTestServer(args.root, args.api, host=args.host, port=args.port)
    port = server.start()
    print(f"webtest on :{port}")
    import signal, threading as t

    stop = t.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
