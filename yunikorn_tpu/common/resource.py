"""Typed resource model with exact integer arithmetic on the host side.

Role-equivalent to the reference's pkg/common/resource.go: ResourceBuilder /
GetPodResource (:34-187) including the init-container max rule, sidecar
(restartPolicy: Always init) handling, and node allocatable conversion (:188-197).

Host-side resources are exact int64-like Python ints in canonical units:
  cpu              -> millicores ("vcore" in SI terms)
  memory           -> bytes
  ephemeral-storage-> bytes
  pods             -> count
  anything else    -> raw integer quantity (e.g. nvidia.com/gpu, google.com/tpu)

Device-side quantization (memory → MiB etc.) is the snapshot encoder's concern,
not this module's.
"""
from __future__ import annotations

import functools
import re
from typing import Dict, Iterable, Mapping, Optional

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
HUGEPAGES_PREFIX = "hugepages-"
# Volume attach limits ride the resource-fit machinery: pods consume one unit
# per PVC volume, nodes default to 64 attachable (the NodeVolumeLimits / CSI
# limits predicate of the reference's allocation plugin set).
VOLUME_ATTACH = "attachable-volumes-csi"
DEFAULT_NODE_VOLUME_LIMIT = 64

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+)([EPTGMKezypnum]i?|)$")

_DECIMAL_SUFFIX = {
    "": 1,
    "k": 10**3, "K": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}
_BINARY_SUFFIX = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}


def parse_quantity(value, as_milli: bool = False) -> int:
    """Parse a K8s-style quantity string into an int (optionally millis).

    Accepts ints/floats directly. Examples: "100m" cpu → 100 (as_milli),
    "2" cpu → 2000 (as_milli), "1Gi" → 1073741824, "500M" → 500000000.
    """
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return int(value * 1000) if as_milli else int(value)
    # quantity strings repeat massively across a cluster ("100m", "1Gi"):
    # the memoized pure parse cuts the per-pod-update host cost at scale
    return _parse_quantity_str(str(value), as_milli)


@functools.lru_cache(maxsize=4096)
def _parse_quantity_str(s: str, as_milli: bool) -> int:
    s = s.strip()
    if not s:
        return 0
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"cannot parse quantity {s!r}")
    num, suffix = m.group(1), m.group(2)
    if suffix == "m":
        milli = float(num)
        return int(milli) if as_milli else int(milli / 1000)
    if suffix in _BINARY_SUFFIX:
        base = float(num) * _BINARY_SUFFIX[suffix]
    elif suffix in _DECIMAL_SUFFIX:
        base = float(num) * _DECIMAL_SUFFIX[suffix]
    else:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {s!r}")
    return int(base * 1000) if as_milli else int(base)


class Resource:
    """An immutable-by-convention map resource-name → int quantity."""

    __slots__ = ("resources",)

    def __init__(self, resources: Optional[Mapping[str, int]] = None):
        self.resources: Dict[str, int] = dict(resources or {})

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_requests(requests: Mapping[str, object]) -> "Resource":
        """Build from a K8s resource-requests mapping (quantity strings allowed)."""
        out: Dict[str, int] = {}
        for name, q in requests.items():
            if name == CPU:
                out[CPU] = parse_quantity(q, as_milli=True)
            else:
                out[name] = parse_quantity(q)
        return Resource(out)

    # -- arithmetic ---------------------------------------------------------
    def add(self, other: "Resource") -> "Resource":
        out = dict(self.resources)
        for k, v in other.resources.items():
            out[k] = out.get(k, 0) + v
        return Resource(out)

    def sub(self, other: "Resource") -> "Resource":
        out = dict(self.resources)
        for k, v in other.resources.items():
            out[k] = out.get(k, 0) - v
        return Resource(out)

    def component_max(self, other: "Resource") -> "Resource":
        """Per-component max (the init-container rule)."""
        out = dict(self.resources)
        for k, v in other.resources.items():
            out[k] = max(out.get(k, 0), v)
        return Resource(out)

    def fits_in(self, capacity: "Resource") -> bool:
        return all(capacity.resources.get(k, 0) >= v for k, v in self.resources.items())

    def within_limit(self, limit: "Resource") -> bool:
        """Quota semantics: only resources the limit names are constrained."""
        return all(self.resources.get(k, 0) <= v for k, v in limit.resources.items())

    def is_zero(self) -> bool:
        return all(v == 0 for v in self.resources.values())

    def get(self, name: str) -> int:
        return self.resources.get(name, 0)

    def clone(self) -> "Resource":
        return Resource(dict(self.resources))

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        keys = set(self.resources) | set(other.resources)
        return all(self.resources.get(k, 0) == other.resources.get(k, 0) for k in keys)

    def __hash__(self):  # pragma: no cover - Resources are not meant as dict keys
        return hash(tuple(sorted((k, v) for k, v in self.resources.items() if v)))

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v}" for k, v in sorted(self.resources.items()))
        return f"Resource({inner})"


class ResourceBuilder:
    """Fluent builder (reference resource.go ResourceBuilder)."""

    def __init__(self):
        self._r: Dict[str, int] = {}

    def add_resource(self, name: str, value: int) -> "ResourceBuilder":
        self._r[name] = self._r.get(name, 0) + int(value)
        return self

    def cpu(self, milli: int) -> "ResourceBuilder":
        return self.add_resource(CPU, milli)

    def memory(self, bytes_: int) -> "ResourceBuilder":
        return self.add_resource(MEMORY, bytes_)

    def pods(self, n: int) -> "ResourceBuilder":
        return self.add_resource(PODS, n)

    def build(self) -> Resource:
        return Resource(self._r)


def get_pod_resource(pod) -> Resource:
    """Compute a pod's effective resource request (reference resource.go:34-187).

    Rules (mirroring K8s semantics the reference implements):
      - base = sum of container requests; sidecar containers (init containers with
        restartPolicy "Always") are added to the base sum;
      - for each non-sidecar init container i: effective = max(effective,
        request(i) + sum(previous sidecars));
      - always counts "pods": 1;
      - if the pod is assigned and has a status-level resize in progress, status
        container resources win over spec (in-place resize).
    """
    total = Resource({PODS: 1})
    n_vols = sum(1 for v in pod.spec.volumes if v.pvc_claim_name)
    if n_vols:
        total = total.add(Resource({VOLUME_ATTACH: n_vols}))
    for c in pod.spec.containers:
        req = _container_request(pod, c)
        total = total.add(req)

    sidecar_sum = Resource()
    effective = total
    for ic in pod.spec.init_containers:
        req = Resource.from_requests(ic.resources_requests or {})
        if (ic.restart_policy or "") == "Always":
            # Sidecar: runs for the pod's lifetime, adds to the running sum.
            sidecar_sum = sidecar_sum.add(req)
            total = total.add(req)
            effective = effective.component_max(total)
        else:
            effective = effective.component_max(req.add(sidecar_sum).add(Resource({PODS: 1})))
    return effective


def _container_request(pod, container) -> Resource:
    # In-place pod resize: prefer allocated resources from status when present
    # (reference resource.go checks PodStatus container statuses during resize).
    status_req = None
    for cs in getattr(pod.status, "container_statuses", []) or []:
        if cs.get("name") == container.name and cs.get("resources"):
            status_req = cs["resources"].get("requests")
            break
    if status_req is not None:
        return Resource.from_requests(status_req)
    return Resource.from_requests(container.resources_requests or {})


def get_node_resource(allocatable: Mapping[str, object]) -> Resource:
    """Node allocatable → Resource (reference resource.go:188-197).

    Injects the default CSI attach limit when the node does not publish one,
    so volume-consuming pods are bounded per node.
    """
    out = Resource.from_requests(allocatable)
    if VOLUME_ATTACH not in out.resources:
        out.resources[VOLUME_ATTACH] = DEFAULT_NODE_VOLUME_LIMIT
    return out


def equals(a: Optional[Resource], b: Optional[Resource]) -> bool:
    if a is None or b is None:
        return a is b
    return a == b


def sum_resources(items: Iterable[Resource]) -> Resource:
    out = Resource()
    for r in items:
        out = out.add(r)
    return out
