"""K8s-lite object model.

The reference consumes `k8s.io/api/core/v1` types through informers. This framework
is cluster-agnostic: it defines its own light-weight typed object model carrying
exactly the fields the scheduling path reads (reference usage sites: pod metadata /
spec in pkg/cache/metadata.go, pkg/common/resource.go, predicate inputs in
pkg/plugin/predicates/predicate_manager.go). A real-K8s adapter can map API objects
onto these dataclasses without touching the rest of the stack.

All objects are plain mutable dataclasses; identity is (namespace, name) + uid.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional

_uid_counter = itertools.count(1)


def generate_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    creation_timestamp: float = 0.0
    owner_references: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    resource_version: int = 0

    def __post_init__(self):
        if not self.uid:
            self.uid = generate_uid(self.name or "obj")
        if not self.creation_timestamp:
            self.creation_timestamp = time.time()


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Container:
    name: str
    resources_requests: Dict[str, Any] = dataclasses.field(default_factory=dict)
    resources_limits: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ports: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # ports entries: {"hostPort": int, "protocol": "TCP", "hostIP": "0.0.0.0"}
    restart_policy: Optional[str] = None  # init containers: "Always" => sidecar


@dataclasses.dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects; NoSchedule | PreferNoSchedule | NoExecute
    toleration_seconds: Optional[int] = None


@dataclasses.dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = dataclasses.field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodAffinityTerm:
    label_selector: Optional[Dict[str, Any]] = None  # {"matchLabels": {...}, "matchExpressions": [...]}
    topology_key: str = ""
    namespaces: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Affinity:
    # requiredDuringSchedulingIgnoredDuringExecution
    node_required_terms: List[NodeSelectorTerm] = dataclasses.field(default_factory=list)
    # preferredDuringScheduling: [(weight, NodeSelectorTerm)]
    node_preferred_terms: List[tuple] = dataclasses.field(default_factory=list)
    pod_affinity_required: List[PodAffinityTerm] = dataclasses.field(default_factory=list)
    pod_affinity_preferred: List[tuple] = dataclasses.field(default_factory=list)
    pod_anti_affinity_required: List[PodAffinityTerm] = dataclasses.field(default_factory=list)
    pod_anti_affinity_preferred: List[tuple] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class ResourceClaim:
    """Dynamic Resource Allocation claim (reference gates a DRA manager into
    the Context, context.go:116-130, and plumbs ResourceClaim informers,
    apifactory.go:39-59). Structured-parameters model: the claim names a
    device class; allocation pins it to one node's devices."""

    name: str = ""
    namespace: str = "default"
    device_class: str = ""
    allocated_node: str = ""      # "" until allocated
    reserved_for: List[str] = dataclasses.field(default_factory=list)  # pod uids

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class ResourceSlice:
    """Per-node device inventory published by a DRA driver (K8s
    ResourceSlice): `count` devices of `device_class` on `node_name`."""

    node_name: str = ""
    device_class: str = ""
    count: int = 0

    @property
    def key(self) -> str:
        return f"{self.node_name}/{self.device_class}"


@dataclasses.dataclass
class Volume:
    name: str = ""
    pvc_claim_name: Optional[str] = None  # persistentVolumeClaim.claimName
    ephemeral: bool = False


@dataclasses.dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = ""
    containers: List[Container] = dataclasses.field(default_factory=list)
    init_containers: List[Container] = dataclasses.field(default_factory=list)
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = dataclasses.field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = dataclasses.field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: Optional[str] = None  # PreemptLowerPriority | Never
    scheduling_gates: List[str] = dataclasses.field(default_factory=list)
    volumes: List[Volume] = dataclasses.field(default_factory=list)
    restart_policy: str = "Always"
    overhead: Dict[str, Any] = dataclasses.field(default_factory=dict)
    service_account: str = ""
    # DRA: names of ResourceClaims (same namespace) this pod requires
    resource_claims: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""


@dataclasses.dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: List[PodCondition] = dataclasses.field(default_factory=list)
    nominated_node_name: str = ""
    container_statuses: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    reason: str = ""


@dataclasses.dataclass
class Pod:
    metadata: ObjectMeta
    spec: PodSpec = dataclasses.field(default_factory=PodSpec)
    status: PodStatus = dataclasses.field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_terminated(self) -> bool:
        return self.status.phase in ("Succeeded", "Failed")

    def is_assigned(self) -> bool:
        return bool(self.spec.node_name)

    def deepcopy(self) -> "Pod":
        """Fast structural clone for event old/new snapshots.

        Copies every layer the system mutates in place — metadata
        label/annotation maps, spec scalars (node_name), container resource
        maps (in-place resize), status phase/conditions — and SHARES the
        immutable-by-K8s-convention constraint objects (affinity,
        tolerations, topology spread constraints, volumes): changing those
        in K8s is a pod replacement, never an in-place patch. ~20x cheaper
        than copy.deepcopy's full graph walk, which dominated the shim
        pipeline's host cost at 50k-pod scale (2 clones per bind).
        """
        md = self.metadata
        new_md = dataclasses.replace(
            md, labels=dict(md.labels), annotations=dict(md.annotations),
            owner_references=list(md.owner_references))
        sp = self.spec
        new_spec = dataclasses.replace(
            sp,
            containers=[dataclasses.replace(
                c, resources_requests=dict(c.resources_requests),
                resources_limits=dict(c.resources_limits))
                for c in sp.containers],
            init_containers=[dataclasses.replace(
                c, resources_requests=dict(c.resources_requests),
                resources_limits=dict(c.resources_limits))
                for c in sp.init_containers],
            node_selector=dict(sp.node_selector),
            scheduling_gates=list(sp.scheduling_gates),
            resource_claims=list(sp.resource_claims),
        )
        st = self.status
        new_status = dataclasses.replace(
            st,
            conditions=[dataclasses.replace(c) for c in st.conditions],
            container_statuses=[dict(cs) for cs in st.container_statuses])
        return Pod(metadata=new_md, spec=new_spec, status=new_status)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclasses.dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeStatus:
    allocatable: Dict[str, Any] = dataclasses.field(default_factory=dict)
    capacity: Dict[str, Any] = dataclasses.field(default_factory=dict)
    conditions: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Node:
    metadata: ObjectMeta
    spec: NodeSpec = dataclasses.field(default_factory=NodeSpec)
    status: NodeStatus = dataclasses.field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def deepcopy(self) -> "Node":
        import copy

        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Other cluster objects the shim watches
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ConfigMap:
    metadata: ObjectMeta
    data: Dict[str, str] = dataclasses.field(default_factory=dict)
    binary_data: Dict[str, bytes] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PriorityClass:
    metadata: ObjectMeta
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclasses.dataclass
class Namespace:
    metadata: ObjectMeta


@dataclasses.dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta
    storage_class: str = ""
    bound: bool = False
    volume_name: str = ""
    # requested storage bytes + access modes for static PV matching
    requested_storage: int = 0
    access_modes: List[str] = dataclasses.field(default_factory=lambda: ["ReadWriteOnce"])
    # original API document (real adapter): encode_pvc merges mutations into
    # a copy of this so full-object PUTs keep volumeMode/selector/resources/
    # resourceVersion — fields the simplified model does not carry
    raw: Optional[Dict[str, Any]] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def selected_node(self) -> str:
        """WaitForFirstConsumer: the node the scheduler picked; an external
        provisioner watches this annotation (volume.kubernetes.io/selected-node)."""
        return self.metadata.annotations.get("volume.kubernetes.io/selected-node", "")


@dataclasses.dataclass
class PersistentVolume:
    """Cluster-scoped volume for static PVC binding (reference relies on the
    K8s volumebinding plugin; here the shim's own binder matches claims)."""
    metadata: ObjectMeta
    capacity: int = 0                       # storage bytes
    access_modes: List[str] = dataclasses.field(default_factory=lambda: ["ReadWriteOnce"])
    storage_class: str = ""
    claim_ref: str = ""                     # "namespace/name" when bound/reserved
    phase: str = "Available"                # Available | Bound | Released
    # simplified node affinity: required node-label matches ({} = any node)
    node_affinity: Dict[str, str] = dataclasses.field(default_factory=dict)
    # original API document (real adapter): encode_pv merges mutations into a
    # copy of this so full-object PUTs keep the volume source (csi/nfs/...)
    # and resourceVersion — a PV without a source fails API validation
    raw: Optional[Dict[str, Any]] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclasses.dataclass
class StorageClass:
    metadata: ObjectMeta
    provisioner: str = ""
    volume_binding_mode: str = "Immediate"  # Immediate | WaitForFirstConsumer

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclasses.dataclass
class CSINodeInfo:
    """Per-node CSI driver attach limits (storage.k8s.io/v1 CSINode)."""
    metadata: ObjectMeta                    # name == node name
    # driver name -> max attachable volume count
    driver_limits: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name

    def total_limit(self) -> Optional[int]:
        if not self.driver_limits:
            return None
        return min(self.driver_limits.values())


@dataclasses.dataclass
class CSIDriverInfo:
    """storage.k8s.io/v1 CSIDriver: per-driver behavior flags. The
    storage_capacity flag gates capacity-aware dynamic provisioning
    (CSIStorageCapacity checks) in the volume binder."""
    metadata: ObjectMeta                    # name == driver name
    attach_required: bool = True
    storage_capacity: bool = False

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclasses.dataclass
class CSIStorageCapacityInfo:
    """storage.k8s.io/v1 CSIStorageCapacity: provisionable capacity of one
    storage class within a node topology segment (matchLabels simplified)."""
    metadata: ObjectMeta
    storage_class: str = ""
    # node topology selector (required matchLabels; {} = all nodes)
    node_topology: Dict[str, str] = dataclasses.field(default_factory=dict)
    capacity: int = 0                       # provisionable bytes
    maximum_volume_size: int = 0            # 0 = no per-volume bound
    # the topology selector used an expression shape the simplified model
    # cannot represent (NotIn / Exists / multi-value In): fail CLOSED —
    # claiming wider coverage would place pods the driver can't serve
    topology_unsupported: bool = False

    def covers_node(self, node: Node) -> bool:
        if self.topology_unsupported:
            return False
        labels = node.metadata.labels
        return all(labels.get(k) == v for k, v in self.node_topology.items())

    def fits(self, requested: int) -> bool:
        if self.maximum_volume_size and requested > self.maximum_volume_size:
            return False
        return requested <= self.capacity


@dataclasses.dataclass
class VolumeAttachmentInfo:
    """storage.k8s.io/v1 VolumeAttachment: a volume attached (or attaching)
    to a node. Attachments whose PV no cache pod on the node mounts count as
    foreign occupancy against the node's attach limit."""
    metadata: ObjectMeta
    attacher: str = ""
    node_name: str = ""
    pv_name: str = ""
    attached: bool = False

    @property
    def name(self) -> str:
        return self.metadata.name


def make_pod(
    name: str,
    namespace: str = "default",
    cpu_milli: int = 0,
    memory: int = 0,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    node_name: str = "",
    scheduler_name: str = "",
    phase: str = "Pending",
    priority: Optional[int] = None,
    extra_resources: Optional[Dict[str, int]] = None,
    **spec_kwargs,
) -> Pod:
    """Test/driver helper to build a pod with one container."""
    requests: Dict[str, Any] = {}
    if cpu_milli:
        requests["cpu"] = f"{cpu_milli}m"
    if memory:
        requests["memory"] = str(memory)
    for k, v in (extra_resources or {}).items():
        requests[k] = v
    return Pod(
        metadata=ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {}),
                            annotations=dict(annotations or {})),
        spec=PodSpec(
            node_name=node_name,
            scheduler_name=scheduler_name,
            containers=[Container(name="c0", resources_requests=requests)],
            priority=priority,
            **spec_kwargs,
        ),
        status=PodStatus(phase=phase),
    )


def make_node(
    name: str,
    cpu_milli: int = 16000,
    memory: int = 16 * 2**30,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    extra_resources: Optional[Dict[str, int]] = None,
    unschedulable: bool = False,
) -> Node:
    """Test/driver helper to build a node."""
    allocatable: Dict[str, Any] = {
        "cpu": f"{cpu_milli}m",
        "memory": str(memory),
        "pods": pods,
    }
    for k, v in (extra_resources or {}).items():
        allocatable[k] = v
    return Node(
        metadata=ObjectMeta(name=name, namespace="", labels=dict(labels or {})),
        spec=NodeSpec(unschedulable=unschedulable, taints=list(taints or [])),
        status=NodeStatus(allocatable=allocatable, capacity=dict(allocatable)),
    )
