"""Scheduler Interface (SI) analog: the shim↔core protocol.

Role-equivalent to apache/yunikorn-scheduler-interface: the message shapes
(AllocationAsk, Allocation, releases, application/node requests) plus the two API
surfaces — `SchedulerAPI` (shim → core; reference api.SchedulerAPI) and
`ResourceManagerCallback` (core → shim; reference api.ResourceManagerCallback,
implemented by pkg/cache/scheduler_callback.go:38-47).

The lifecycle code on both sides speaks only these types; tensors never cross this
boundary. That keeps the reference's architectural seam: the TPU batched solver is
an implementation detail of the core, exactly as YuniKorn's queue logic is behind
the SI in the reference.
"""
from __future__ import annotations

import abc
import dataclasses
import enum
from typing import Dict, List, Optional

from yunikorn_tpu.common.resource import Resource


class TerminationType(str, enum.Enum):
    """Why an allocation was released (SI si.TerminationType)."""

    STOPPED_BY_RM = "STOPPED_BY_RM"
    TIMEOUT = "TIMEOUT"
    PREEMPTED_BY_SCHEDULER = "PREEMPTED_BY_SCHEDULER"
    PLACEHOLDER_REPLACED = "PLACEHOLDER_REPLACED"
    UNKNOWN_ALLOCATION = "UNKNOWN_ALLOCATION"


class NodeAction(str, enum.Enum):
    """Node lifecycle actions (SI NodeInfo.ActionFromRM)."""

    CREATE = "CREATE"
    UPDATE = "UPDATE"
    DRAIN_TO_SCHEDULABLE = "DRAIN_TO_SCHEDULABLE"
    DRAIN_NODE = "DRAIN_NODE"
    DECOMISSION = "DECOMISSION"
    CREATE_DRAIN = "CREATE_DRAIN"


@dataclasses.dataclass
class UserGroupInfo:
    user: str = ""
    groups: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TaskGroup:
    """Gang task-group definition (parsed from the task-groups annotation)."""

    name: str
    min_member: int
    min_resource: Dict[str, object] = dataclasses.field(default_factory=dict)
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: List[object] = dataclasses.field(default_factory=list)
    affinity: Optional[object] = None
    topology_spread_constraints: List[object] = dataclasses.field(default_factory=list)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AllocationAsk:
    """A pending request for one allocation (SI si.Allocation with no node)."""

    allocation_key: str                  # == pod UID in the shim
    application_id: str
    resource: Resource
    priority: int = 0
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    placeholder: bool = False
    task_group_name: str = ""
    originator: bool = False
    preferred_node: str = ""
    pod: Optional[object] = None         # opaque to the core's policy, used by predicates
    seq: int = 0                         # core-assigned FIFO sequence


@dataclasses.dataclass
class Allocation:
    """A decided or recovered allocation (ask + node)."""

    allocation_key: str
    application_id: str
    node_id: str
    resource: Resource
    priority: int = 0
    placeholder: bool = False
    task_group_name: str = ""
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    # For foreign (non-YuniKorn) pods tracked as occupied resource:
    foreign: bool = False
    preemptable: bool = True


@dataclasses.dataclass
class AllocationRelease:
    application_id: str
    allocation_key: str
    termination_type: TerminationType = TerminationType.STOPPED_BY_RM
    message: str = ""


@dataclasses.dataclass
class AllocationRequest:
    """Shim→core allocation update (asks + releases), reference si_helper.go:75-231."""

    asks: List[AllocationAsk] = dataclasses.field(default_factory=list)
    allocations: List[Allocation] = dataclasses.field(default_factory=list)  # existing/recovered/foreign
    releases: List[AllocationRelease] = dataclasses.field(default_factory=list)
    rm_id: str = ""


@dataclasses.dataclass
class ApplicationRequest:
    """Shim→core application submission / removal."""

    new: List["AddApplicationRequest"] = dataclasses.field(default_factory=list)
    remove: List["RemoveApplicationRequest"] = dataclasses.field(default_factory=list)
    rm_id: str = ""


@dataclasses.dataclass
class AddApplicationRequest:
    application_id: str
    queue_name: str
    partition: str = "default"
    user: UserGroupInfo = dataclasses.field(default_factory=UserGroupInfo)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    placeholder_ask: Optional[Resource] = None
    task_groups: List[TaskGroup] = dataclasses.field(default_factory=list)
    gang_scheduling_style: str = "Soft"
    execution_timeout_seconds: Optional[float] = None


@dataclasses.dataclass
class RemoveApplicationRequest:
    application_id: str
    partition: str = "default"


@dataclasses.dataclass
class NodeInfo:
    node_id: str
    action: NodeAction
    attributes: Dict[str, str] = dataclasses.field(default_factory=dict)
    schedulable_resource: Optional[Resource] = None
    occupied_resource: Optional[Resource] = None
    existing_allocations: List[Allocation] = dataclasses.field(default_factory=list)
    node: Optional[object] = None        # the Node object, for predicate encoding


@dataclasses.dataclass
class NodeRequest:
    nodes: List[NodeInfo] = dataclasses.field(default_factory=list)
    rm_id: str = ""


@dataclasses.dataclass
class RegisterResourceManagerRequest:
    rm_id: str
    policy_group: str
    version: str = ""
    build_info: Dict[str, str] = dataclasses.field(default_factory=dict)
    config: str = ""                      # opaque queues.yaml payload
    extra_config: Dict[str, str] = dataclasses.field(default_factory=dict)


# ---------------------------------------------------------------------------
# Core → shim response shapes (subset of si.UpdateResponse the shim consumes,
# reference pkg/cache/scheduler_callback.go)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RejectedAllocationAsk:
    application_id: str
    allocation_key: str
    reason: str = ""


@dataclasses.dataclass
class AllocationResponse:
    new: List[Allocation] = dataclasses.field(default_factory=list)
    released: List[AllocationRelease] = dataclasses.field(default_factory=list)
    rejected: List[RejectedAllocationAsk] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AcceptedApplication:
    application_id: str


@dataclasses.dataclass
class RejectedApplication:
    application_id: str
    reason: str = ""


@dataclasses.dataclass
class UpdatedApplication:
    application_id: str
    state: str = ""
    message: str = ""


@dataclasses.dataclass
class ApplicationResponse:
    accepted: List[AcceptedApplication] = dataclasses.field(default_factory=list)
    rejected: List[RejectedApplication] = dataclasses.field(default_factory=list)
    updated: List[UpdatedApplication] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AcceptedNode:
    node_id: str


@dataclasses.dataclass
class RejectedNode:
    node_id: str
    reason: str = ""


@dataclasses.dataclass
class NodeResponse:
    accepted: List[AcceptedNode] = dataclasses.field(default_factory=list)
    rejected: List[RejectedNode] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PredicatesArgs:
    """Per-(pod,node) feasibility probe (SI si.PredicatesArgs).

    Retained for API parity and used by preemption; the batched solver evaluates
    these in bulk on device instead of one upcall per probe (reference hot loop:
    scheduler_callback.go:196-198).
    """

    allocation_key: str
    node_id: str
    allocate: bool = True


@dataclasses.dataclass
class PreemptionPredicatesArgs:
    allocation_key: str
    node_id: str
    preempt_allocation_keys: List[str] = dataclasses.field(default_factory=list)
    start_index: int = 0


@dataclasses.dataclass
class PreemptionPredicatesResponse:
    success: bool = False
    index: int = -1


class EventRecordType(str, enum.Enum):
    REQUEST = "REQUEST"
    APP = "APP"
    NODE = "NODE"
    QUEUE = "QUEUE"
    USERGROUP = "USERGROUP"


@dataclasses.dataclass
class EventRecord:
    type: EventRecordType
    object_id: str
    reference_id: str = ""
    reason: str = ""
    message: str = ""


class ContainerSchedulingState(str, enum.Enum):
    """Autoscaler integration (si.UpdateContainerSchedulingStateRequest)."""

    SKIPPED = "SKIPPED"
    FAILED = "FAILED"
    RESERVED = "RESERVED"


@dataclasses.dataclass
class UpdateContainerSchedulingStateRequest:
    application_id: str
    allocation_key: str
    state: ContainerSchedulingState
    reason: str = ""


# ---------------------------------------------------------------------------
# The two API surfaces
# ---------------------------------------------------------------------------

class SchedulerAPI(abc.ABC):
    """Shim → core (reference api.SchedulerAPI)."""

    @abc.abstractmethod
    def register_resource_manager(
        self, request: RegisterResourceManagerRequest, callback: "ResourceManagerCallback"
    ) -> None: ...

    @abc.abstractmethod
    def update_allocation(self, request: AllocationRequest) -> None: ...

    @abc.abstractmethod
    def update_application(self, request: ApplicationRequest) -> None: ...

    @abc.abstractmethod
    def update_node(self, request: NodeRequest) -> None: ...

    @abc.abstractmethod
    def update_configuration(self, config: str, extra_config: Dict[str, str]) -> None: ...


class ResourceManagerCallback(abc.ABC):
    """Core → shim (reference api.ResourceManagerCallback)."""

    @abc.abstractmethod
    def update_allocation(self, response: AllocationResponse) -> None: ...

    @abc.abstractmethod
    def update_application(self, response: ApplicationResponse) -> None: ...

    @abc.abstractmethod
    def update_node(self, response: NodeResponse) -> None: ...

    @abc.abstractmethod
    def predicates(self, args: PredicatesArgs) -> Optional[str]:
        """Return None when the pod fits the node, else a failure reason."""

    @abc.abstractmethod
    def preemption_predicates(
        self, args: PreemptionPredicatesArgs
    ) -> PreemptionPredicatesResponse: ...

    @abc.abstractmethod
    def send_event(self, events: List[EventRecord]) -> None: ...

    @abc.abstractmethod
    def update_container_scheduling_state(
        self, request: UpdateContainerSchedulingStateRequest
    ) -> None: ...

    @abc.abstractmethod
    def get_state_dump(self) -> str: ...
