"""Scheduling event interfaces + the cluster event recorder.

Role-equivalent to pkg/common/events/events.go:26-76 (SchedulingEvent /
ApplicationEvent / TaskEvent / SchedulerNodeEvent interfaces) and recorder.go:27-43
(the global K8s event recorder the shim emits lifecycle events through).
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, List, Optional, Tuple

from yunikorn_tpu.locking import locking
from yunikorn_tpu.log.logger import log

logger = log("shim.utils")


class SchedulingEvent:
    """Marker base; every dispatched event carries optional args."""

    def get_args(self) -> Tuple[Any, ...]:
        return getattr(self, "args", ())


class ApplicationEvent(SchedulingEvent):
    def get_application_id(self) -> str:
        raise NotImplementedError

    def get_event(self) -> str:
        raise NotImplementedError


class TaskEvent(SchedulingEvent):
    def get_application_id(self) -> str:
        raise NotImplementedError

    def get_task_id(self) -> str:
        raise NotImplementedError

    def get_event(self) -> str:
        raise NotImplementedError


class SchedulerNodeEvent(SchedulingEvent):
    def get_node_id(self) -> str:
        raise NotImplementedError

    def get_event(self) -> str:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Simple generic event implementations (the reference declares one struct per
# event type in application_state.go:63-326 / task_state.go; a single generic
# record with the same accessors serves all of them)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AppEventRecord(ApplicationEvent):
    application_id: str
    event: str
    args: Tuple[Any, ...] = ()

    def get_application_id(self) -> str:
        return self.application_id

    def get_event(self) -> str:
        return self.event


@dataclasses.dataclass
class TaskEventRecord(TaskEvent):
    application_id: str
    task_id: str
    event: str
    args: Tuple[Any, ...] = ()

    def get_application_id(self) -> str:
        return self.application_id

    def get_task_id(self) -> str:
        return self.task_id

    def get_event(self) -> str:
        return self.event


@dataclasses.dataclass
class NodeEventRecord(SchedulerNodeEvent):
    node_id: str
    event: str
    args: Tuple[Any, ...] = ()

    def get_node_id(self) -> str:
        return self.node_id

    def get_event(self) -> str:
        return self.event


# ---------------------------------------------------------------------------
# Event recorder (K8s Events analog)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RecordedEvent:
    object_kind: str       # "Pod" | "Node" | ...
    object_key: str        # namespace/name or node name
    event_type: str        # "Normal" | "Warning"
    reason: str
    message: str
    timestamp: float = dataclasses.field(default_factory=time.time)


class EventRecorder:
    """In-memory recorder; a real-K8s adapter would forward to the Events API.

    The reference installs a fake recorder in tests and a real one in main
    (events/recorder.go; shim/scheduler.go:154-163). Here the in-memory recorder
    *is* the default, and doubles as the assertion surface for tests.
    """

    def __init__(self, capacity: int = 100000):
        self._lock = locking.Mutex()
        # deque(maxlen): O(1) eviction — a bench cycle emits several events
        # per pod, and list.pop(0) at capacity is O(capacity) each
        self._events: collections.deque = collections.deque(maxlen=capacity)

    def eventf(self, object_kind: str, object_key: str, event_type: str, reason: str,
               message: str, *fmt_args) -> None:
        if fmt_args:
            try:
                message = message % fmt_args
            except TypeError:
                message = f"{message} {fmt_args}"
        with self._lock:
            self._events.append(RecordedEvent(object_kind, object_key, event_type, reason, message))

    def events(self, object_key: Optional[str] = None, reason: Optional[str] = None) -> List[RecordedEvent]:
        with self._lock:
            out = list(self._events)
        if object_key is not None:
            out = [e for e in out if e.object_key == object_key]
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


_recorder_lock = locking.Mutex()
_recorder: Optional[EventRecorder] = None


def get_recorder() -> EventRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = EventRecorder()
        return _recorder


def set_recorder(rec: EventRecorder) -> None:
    global _recorder
    with _recorder_lock:
        _recorder = rec
