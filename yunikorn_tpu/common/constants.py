"""Shared constants: labels, annotations, scheduler identity, gang parameters.

Role-equivalent to the reference's pkg/common/constants/constants.go. The domain
prefixes are kept wire-compatible so workloads labeled for the reference scheduler
work unchanged against this framework.
"""

TRUE = "true"
FALSE = "false"

DOMAIN = "yunikorn.apache.org/"
DOMAIN_INTERNAL = "yunikorn.apache.org/internal-"

# Cluster / node attributes
NODE_ATTRIBUTE_HOSTNAME = "si.io/hostname"
NODE_ATTRIBUTE_RACKNAME = "si.io/rackname"
NODE_INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
DEFAULT_RACK = "/rack-default"

# Application identification (resolution order mirrors utils.GetApplicationIDFromPod,
# reference pkg/common/utils/utils.go:141-188)
LABEL_APP = "app"
LABEL_APPLICATION_ID = "applicationId"
CANONICAL_LABEL_APP_ID = DOMAIN + "app-id"
ANNOTATION_APP_ID = DOMAIN + "app-id"
LABEL_QUEUE_NAME = "queue"
CANONICAL_LABEL_QUEUE_NAME = DOMAIN + "queue"
ANNOTATION_QUEUE_NAME = DOMAIN + "queue"
ANNOTATION_PARENT_QUEUE = DOMAIN + "parentqueue"
# multi-partition routing (extension beyond the single-partition reference
# shim): node label → SI node attribute → core partition router
LABEL_NODE_PARTITION = DOMAIN + "node-partition"
ANNOTATION_PARTITION = DOMAIN + "partition"
SI_NODE_PARTITION = "si/node-partition"
LABEL_SPARK_APP_ID = "spark-app-selector"

ROOT_QUEUE = "root"
DEFAULT_PARTITION = "default"
APP_TAG_NAMESPACE = "namespace"
APP_TAG_NAMESPACE_PARENT_QUEUE = "namespace.parentqueue"
APP_TAG_IMAGE_PULL_SECRETS = "imagePullSecrets"
DEFAULT_APP_NAMESPACE = "default"
DEFAULT_USER_LABEL = DOMAIN + "username"
DEFAULT_USER = "nobody"

# Scheduler identity / config
SCHEDULER_NAME = "yunikorn"
CONFIGMAP_NAME = "yunikorn-configs"
DEFAULT_CONFIGMAP_NAME = "yunikorn-defaults"

# Gang scheduling
PLACEHOLDER_CONTAINER_IMAGE = "registry.k8s.io/pause:3.7"
PLACEHOLDER_CONTAINER_NAME = "pause"
PLACEHOLDER_POD_RESTART_POLICY = "Never"
ANNOTATION_PLACEHOLDER_FLAG = DOMAIN_INTERNAL + "placeholder"
ANNOTATION_TASK_GROUP_NAME = DOMAIN + "task-group-name"
ANNOTATION_TASK_GROUPS = DOMAIN + "task-groups"
ANNOTATION_SCHED_POLICY_PARAM = DOMAIN + "schedulingPolicyParameters"
SCHED_POLICY_TIMEOUT_PARAM = "placeholderTimeoutInSeconds"
SCHED_POLICY_PARAM_DELIMITER = " "
SCHED_POLICY_STYLE_PARAM = "gangSchedulingStyle"
GANG_STYLE_SOFT = "Soft"
GANG_STYLE_HARD = "Hard"
GANG_STYLES = (GANG_STYLE_SOFT, GANG_STYLE_HARD)

APP_FAIL_RESERVATION_TIMEOUT = "ResourceReservationTimeout"
APP_FAIL_REJECTED = "ApplicationRejected"

# Namespace quota annotations
NAMESPACE_QUOTA = DOMAIN + "namespace.quota"
NAMESPACE_GUARANTEED = DOMAIN + "namespace.guaranteed"
NAMESPACE_MAX_APPS = DOMAIN + "namespace.maxApps"
CPU_QUOTA_LEGACY = DOMAIN + "namespace.max.cpu"
MEM_QUOTA_LEGACY = DOMAIN + "namespace.max.memory"

# PriorityClass / preemption
ANNOTATION_ALLOW_PREEMPTION = DOMAIN + "allow-preemption"

# Admission
ANNOTATION_GENERATE_APP_ID = DOMAIN + "namespace.generateAppId"
ANNOTATION_ENABLE_YUNIKORN = DOMAIN + "namespace.enableYuniKorn"
ANNOTATION_USER_INFO = DOMAIN + "user.info"
ANNOTATION_IGNORE_APPLICATION = DOMAIN_INTERNAL + "ignore-application"

# OwnerReferences
DAEMONSET_KIND = "DaemonSet"
NODE_KIND = "Node"

# Taints
TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"
