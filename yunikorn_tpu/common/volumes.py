"""Shared volume-matching predicate.

One definition of "this PV can satisfy this claim (on this node)" used by
both the VolumeBinder (find/assume/bind, cache/context.py) and the snapshot
encoder's vectorized volume feasibility mask (snapshot/encoder.py) — the two
callers must never drift, or the solver steers pods to nodes the binder then
rejects. Reference equivalent: the volumebinding plugin's PV matching inside
the Predicates upcall (predicate_manager.go:302-392).
"""
from __future__ import annotations

from typing import Callable, Optional


def node_matches_pv_affinity(pv, node) -> bool:
    if node is None or not pv.node_affinity:
        return True
    labels = node.metadata.labels
    return all(labels.get(k) == v for k, v in pv.node_affinity.items())


def pv_matches_claim(pv, pvc, node, claim_key: str,
                     reserved: Optional[Callable[[str], Optional[str]]] = None) -> bool:
    """Can `pv` satisfy `pvc` (optionally: on `node`)?

    reserved: optional lookup pv-name -> claim key holding an assume-time
    reservation; a PV reserved for another claim is unavailable.
    """
    if pv.claim_ref and pv.claim_ref != claim_key:
        return False
    if not pv.claim_ref and pv.phase != "Available":
        return False
    if reserved is not None:
        holder = reserved(pv.metadata.name)
        if holder is not None and holder != claim_key:
            return False
    if (pvc.storage_class or pv.storage_class) and pvc.storage_class != pv.storage_class:
        return False
    if pvc.requested_storage and pv.capacity < pvc.requested_storage:
        return False
    if not set(pvc.access_modes) <= set(pv.access_modes):
        return False
    return node_matches_pv_affinity(pv, node)
