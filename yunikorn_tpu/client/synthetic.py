"""Synthetic cluster/workload generators: the kwok-perf-test analog.

Reference: deployments/kwok-perf-test/kwok-setup.sh:30-60 (N fake nodes with
32 CPU / 256 Gi / 110 pods) and deploy-tool.sh:35-67 (sleep-pod deployments
labeled applicationId + queue). These helpers produce the same shapes against
FakeCluster for benchmarks and tests, covering the five BASELINE.md configs.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Node, Pod, Taint, make_node, make_pod


def make_kwok_nodes(
    count: int,
    cpu_milli: int = 32000,
    memory: int = 256 * 2**30,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    name_prefix: str = "kwok-node",
) -> List[Node]:
    base_labels = {"type": "kwok", "kubernetes.io/role": "agent"}
    base_labels.update(labels or {})
    return [
        make_node(
            f"{name_prefix}-{i}",
            cpu_milli=cpu_milli,
            memory=memory,
            pods=pods,
            labels=dict(base_labels),
        )
        for i in range(count)
    ]


def make_sleep_pods(
    count: int,
    app_id: str,
    queue: str = "root.default",
    namespace: str = "default",
    cpu_milli: int = 100,
    memory: int = 50 * 2**20,
    name_prefix: Optional[str] = None,
    priority: Optional[int] = None,
    extra_labels: Optional[Dict[str, str]] = None,
) -> List[Pod]:
    prefix = name_prefix or f"{app_id}-pod"
    labels = {
        constants.LABEL_APPLICATION_ID: app_id,
        constants.LABEL_QUEUE_NAME: queue,
    }
    labels.update(extra_labels or {})
    return [
        make_pod(
            f"{prefix}-{i}",
            namespace=namespace,
            cpu_milli=cpu_milli,
            memory=memory,
            labels=dict(labels),
            scheduler_name=constants.SCHEDULER_NAME,
            priority=priority,
        )
        for i in range(count)
    ]


def make_mixed_binpack_pods(
    count: int,
    app_id: str,
    queue: str = "root.default",
    seed: int = 0,
    gpu_fraction: float = 0.3,
) -> List[Pod]:
    """Config #5 workload: GPU+CPU+mem pods with varied shapes."""
    rng = random.Random(seed)
    pods = []
    for i in range(count):
        has_gpu = rng.random() < gpu_fraction
        pod = make_pod(
            f"{app_id}-mix-{i}",
            cpu_milli=rng.choice([250, 500, 1000, 2000, 4000]),
            memory=rng.choice([2**28, 2**29, 2**30, 2**31]),
            labels={
                constants.LABEL_APPLICATION_ID: app_id,
                constants.LABEL_QUEUE_NAME: queue,
            },
            scheduler_name=constants.SCHEDULER_NAME,
            extra_resources={"nvidia.com/gpu": rng.choice([1, 2, 4])} if has_gpu else None,
        )
        pods.append(pod)
    return pods


def make_rich_constraint_pods(
    n_plain: int,
    n_spread: int = 0,
    n_anti: int = 0,
    n_hostmask: int = 0,
    n_soft: int = 0,
    name_prefix: str = "",
) -> List[Pod]:
    """A constraint mix covering every solve channel: plain pods, hard
    topology spread (locality), pod anti-affinity (locality), >MAX_TERMS node
    affinity (host-mask fallback), and preferred node affinity (soft scores).
    Shared by tests/test_parallel.py and __graft_entry__.dryrun_multichip so
    the driver's multichip validation and CI cover the same channels.
    Nodes are expected to carry zone (z0..z3) and kubernetes.io/hostname
    labels (make_kwok_nodes + zone stamping, or make_node with labels).
    """
    from yunikorn_tpu.common.objects import (Affinity, NodeSelectorRequirement,
                                             NodeSelectorTerm, PodAffinityTerm,
                                             TopologySpreadConstraint)

    pods = []
    for i in range(n_plain):
        pods.append(make_pod(f"{name_prefix}plain{i}",
                             cpu_milli=100 + 50 * (i % 4), memory=2**26))
    for i in range(n_spread):
        p = make_pod(f"{name_prefix}spread{i}", cpu_milli=200, memory=2**26)
        p.metadata.labels["grp"] = "spread"
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=2, topology_key="zone", when_unsatisfiable="DoNotSchedule",
            label_selector={"matchLabels": {"grp": "spread"}})]
        pods.append(p)
    for i in range(n_anti):
        p = make_pod(f"{name_prefix}anti{i}", cpu_milli=200, memory=2**26)
        p.metadata.labels["grp"] = "anti"
        p.spec.affinity = Affinity(pod_anti_affinity_required=[PodAffinityTerm(
            label_selector={"matchLabels": {"grp": "anti"}},
            topology_key="kubernetes.io/hostname")])
        pods.append(p)
    for i in range(n_hostmask):
        p = make_pod(f"{name_prefix}hostm{i}", cpu_milli=200, memory=2**26)
        # 9 OR terms > snapshot.encoder.MAX_TERMS (8): the whole affinity
        # falls back to the host-mask channel
        p.spec.affinity = Affinity(node_required_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement("zone", "In", [f"z{t % 4}"])])
            for t in range(9)])
        pods.append(p)
    for i in range(n_soft):
        p = make_pod(f"{name_prefix}soft{i}", cpu_milli=200, memory=2**26)
        p.spec.affinity = Affinity(node_preferred_terms=[
            (50, NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement("zone", "In", ["z1"])]))])
        pods.append(p)
    return pods
