"""Client interfaces: KubeClient + APIProvider.

Role-equivalent to pkg/client/interfaces.go (KubeClient: Bind/Create/Delete/
UpdateStatus/...) and pkg/client/apifactory.go:64-73 (APIProvider: typed informer
access + handler registration). The production implementation against a real
cluster is an adapter concern; the in-repo implementation is FakeCluster
(client/fake.py), which doubles as the MockScheduler-style test harness and the
kwok-style perf driver (reference pkg/client/apifactory_mock.go, kubeclient_mock.go).
"""
from __future__ import annotations

import abc
import enum
from typing import Callable, List, Optional

from yunikorn_tpu.common.objects import ConfigMap, Node, Pod, PriorityClass


class InformerType(enum.Enum):
    POD = "pod"
    NODE = "node"
    CONFIGMAP = "configmap"
    PRIORITY_CLASS = "priorityclass"
    NAMESPACE = "namespace"
    PVC = "pvc"
    STORAGE_CLASS = "storageclass"
    SERVICE = "service"
    REPLICATION_CONTROLLER = "replicationcontroller"
    REPLICASET = "replicaset"
    STATEFULSET = "statefulset"
    DEPLOYMENT = "deployment"
    DAEMONSET = "daemonset"
    JOB = "job"
    CSINODE = "csinode"
    PV = "pv"
    CSI_DRIVER = "csidriver"
    CSI_STORAGE_CAPACITY = "csistoragecapacity"
    VOLUME_ATTACHMENT = "volumeattachment"
    # DRA informers (reference apifactory.go:39-59 when the
    # DynamicResourceAllocation gate is on)
    RESOURCE_CLAIM = "resourceclaim"
    RESOURCE_SLICE = "resourceslice"


class ResourceEventHandlers:
    """add/update/delete callbacks with an optional filter (client-go style)."""

    def __init__(
        self,
        filter_fn: Optional[Callable[[object], bool]] = None,
        add_fn: Optional[Callable[[object], None]] = None,
        update_fn: Optional[Callable[[object, object], None]] = None,
        delete_fn: Optional[Callable[[object], None]] = None,
    ):
        self.filter_fn = filter_fn
        self.add_fn = add_fn
        self.update_fn = update_fn
        self.delete_fn = delete_fn


class KubeClient(abc.ABC):
    """Cluster mutation surface (reference pkg/client/interfaces.go:27)."""

    @abc.abstractmethod
    def bind(self, pod: Pod, node_name: str) -> None:
        """Bind a pod to a node (pods/binding subresource analog)."""

    @abc.abstractmethod
    def create(self, pod: Pod) -> Pod: ...

    @abc.abstractmethod
    def delete(self, pod: Pod) -> None: ...

    @abc.abstractmethod
    def update_pod_condition(self, pod: Pod, condition) -> bool: ...

    @abc.abstractmethod
    def get_configmap(self, namespace: str, name: str) -> Optional[ConfigMap]: ...


class APIProvider(abc.ABC):
    """Informer access + lifecycle (reference apifactory.go:64-73)."""

    @abc.abstractmethod
    def add_event_handler(self, informer: InformerType, handlers: ResourceEventHandlers) -> None: ...

    @abc.abstractmethod
    def get_client(self) -> KubeClient: ...

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def wait_for_sync(self) -> None: ...

    @abc.abstractmethod
    def list_pods(self) -> List[Pod]: ...

    @abc.abstractmethod
    def list_nodes(self) -> List[Node]: ...

    @abc.abstractmethod
    def list_priority_classes(self) -> List[PriorityClass]: ...
