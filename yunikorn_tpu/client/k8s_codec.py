"""K8s REST JSON ↔ internal object codec for the real-cluster adapter.

The reference consumes typed client-go objects; this framework's internal
model is the plain dataclasses in common/objects.py, so the adapter decodes
the API server's JSON straight into them (and encodes pods for Create — the
placeholder path). Only the fields the scheduler consumes are mapped; unknown
fields are ignored, matching an informer's tolerance of newer API versions.

Reference parity: pkg/client consumes Pod/Node/ConfigMap/PriorityClass/
Namespace/PVC informer objects (apifactory.go:39-59); the field set decoded
here is exactly what cache/context.py + the snapshot encoder read.
"""
from __future__ import annotations

import calendar
import copy
import time
from typing import Any, Dict, List, Optional

from yunikorn_tpu.common.objects import (
    Affinity,
    ConfigMap,
    Container,
    Namespace,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodCondition,
    PodSpec,
    PodStatus,
    PriorityClass,
    ResourceClaim,
    ResourceSlice,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    NodeSpec,
    NodeStatus,
)


def _meta(doc: Dict[str, Any]) -> ObjectMeta:
    m = doc.get("metadata") or {}
    ts = m.get("creationTimestamp") or ""
    created = 0.0
    if ts:
        try:
            # creationTimestamp is UTC; timegm, not mktime (which would skew
            # by the host's UTC offset and scramble age-based orderings)
            created = float(calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")))
        except ValueError:
            created = 0.0
    try:
        rv = int(m.get("resourceVersion", 0) or 0)
    except ValueError:
        rv = 0
    return ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default"),
        uid=m.get("uid", ""),
        labels=dict(m.get("labels") or {}),
        annotations=dict(m.get("annotations") or {}),
        creation_timestamp=created,
        owner_references=list(m.get("ownerReferences") or []),
        resource_version=rv,
    )


def _nsr(doc: Dict[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=doc.get("key", ""),
        operator=doc.get("operator", "In"),
        values=list(doc.get("values") or []),
    )


def _node_term(doc: Dict[str, Any]) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=[_nsr(e) for e in doc.get("matchExpressions") or []],
        match_fields=[_nsr(e) for e in doc.get("matchFields") or []],
    )


def _pod_term(doc: Dict[str, Any]) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=doc.get("labelSelector"),
        topology_key=doc.get("topologyKey", ""),
        namespaces=list(doc.get("namespaces") or []),
    )


def _affinity(doc: Optional[Dict[str, Any]]) -> Optional[Affinity]:
    if not doc:
        return None
    out = Affinity()
    na = doc.get("nodeAffinity") or {}
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    out.node_required_terms = [_node_term(t) for t in req.get("nodeSelectorTerms") or []]
    out.node_preferred_terms = [
        (p.get("weight", 1), _node_term(p.get("preference") or {}))
        for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    ]
    pa = doc.get("podAffinity") or {}
    out.pod_affinity_required = [
        _pod_term(t) for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []]
    out.pod_affinity_preferred = [
        (p.get("weight", 1), _pod_term(p.get("podAffinityTerm") or {}))
        for p in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    ]
    ap = doc.get("podAntiAffinity") or {}
    out.pod_anti_affinity_required = [
        _pod_term(t) for t in ap.get("requiredDuringSchedulingIgnoredDuringExecution") or []]
    out.pod_anti_affinity_preferred = [
        (p.get("weight", 1), _pod_term(p.get("podAffinityTerm") or {}))
        for p in ap.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    ]
    if (out.node_required_terms or out.node_preferred_terms
            or out.pod_affinity_required or out.pod_affinity_preferred
            or out.pod_anti_affinity_required or out.pod_anti_affinity_preferred):
        return out
    return None


def _container(doc: Dict[str, Any]) -> Container:
    res = doc.get("resources") or {}
    return Container(
        name=doc.get("name", ""),
        resources_requests=dict(res.get("requests") or {}),
        resources_limits=dict(res.get("limits") or {}),
        ports=[dict(p) for p in doc.get("ports") or []],
        restart_policy=doc.get("restartPolicy"),
    )


def decode_pod(doc: Dict[str, Any]) -> Pod:
    spec_doc = doc.get("spec") or {}
    status_doc = doc.get("status") or {}
    spec = PodSpec(
        node_name=spec_doc.get("nodeName", ""),
        scheduler_name=spec_doc.get("schedulerName", ""),
        containers=[_container(c) for c in spec_doc.get("containers") or []],
        init_containers=[_container(c) for c in spec_doc.get("initContainers") or []],
        node_selector=dict(spec_doc.get("nodeSelector") or {}),
        affinity=_affinity(spec_doc.get("affinity")),
        tolerations=[
            Toleration(key=t.get("key", ""), operator=t.get("operator", "Equal"),
                       value=t.get("value", ""), effect=t.get("effect", ""),
                       toleration_seconds=t.get("tolerationSeconds"))
            for t in spec_doc.get("tolerations") or []
        ],
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=t.get("maxSkew", 1),
                topology_key=t.get("topologyKey", ""),
                when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=t.get("labelSelector"),
            )
            for t in spec_doc.get("topologySpreadConstraints") or []
        ],
        priority=spec_doc.get("priority"),
        priority_class_name=spec_doc.get("priorityClassName", ""),
        preemption_policy=spec_doc.get("preemptionPolicy"),
        scheduling_gates=[g.get("name", "") for g in spec_doc.get("schedulingGates") or []],
        volumes=[
            Volume(name=v.get("name", ""),
                   pvc_claim_name=(v.get("persistentVolumeClaim") or {}).get("claimName", ""))
            for v in spec_doc.get("volumes") or []
        ],
        restart_policy=spec_doc.get("restartPolicy", "Always"),
        overhead=dict(spec_doc.get("overhead") or {}),
        service_account=spec_doc.get("serviceAccountName", ""),
        resource_claims=[c.get("resourceClaimName") or c.get("name", "")
                         for c in spec_doc.get("resourceClaims") or []],
    )
    status = PodStatus(
        phase=status_doc.get("phase", "Pending"),
        reason=status_doc.get("reason", ""),
        conditions=[
            PodCondition(type=c.get("type", ""), status=c.get("status", ""),
                         reason=c.get("reason", ""), message=c.get("message", ""))
            for c in status_doc.get("conditions") or []
        ],
    )
    return Pod(metadata=_meta(doc), spec=spec, status=status)


def encode_pod(pod: Pod) -> Dict[str, Any]:
    """Pod → K8s JSON for Create (the placeholder-pod path; reference
    placeholder.go:41-163 builds typed pods for Create)."""
    containers = []
    for c in pod.spec.containers:
        containers.append({
            "name": c.name,
            "image": getattr(c, "image", "") or "registry.k8s.io/pause:3.7",
            "resources": {"requests": dict(c.resources_requests),
                          "limits": dict(c.resources_limits)},
        })
    spec: Dict[str, Any] = {
        "schedulerName": pod.spec.scheduler_name,
        "containers": containers,
        "restartPolicy": pod.spec.restart_policy,
    }
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {k: v for k, v in (
                ("key", t.key), ("operator", t.operator), ("value", t.value),
                ("effect", t.effect), ("tolerationSeconds", t.toleration_seconds),
            ) if v not in ("", None)}
            for t in pod.spec.tolerations
        ]
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "labels": dict(pod.metadata.labels),
            "annotations": dict(pod.metadata.annotations),
        },
        "spec": spec,
    }


def decode_node(doc: Dict[str, Any]) -> Node:
    from yunikorn_tpu.topology.model import normalize_topology_labels

    spec_doc = doc.get("spec") or {}
    status_doc = doc.get("status") or {}
    meta = _meta(doc)
    # fold provider-specific topology labels (GKE TPU slice/ICI labels,
    # topology.kubernetes.io/rack) into the canonical topology.yunikorn.io/*
    # set here, at the adapter boundary, so the snapshot encoder and the
    # topology scorer only ever parse one label vocabulary
    meta.labels = normalize_topology_labels(meta.labels)
    return Node(
        metadata=meta,
        spec=NodeSpec(
            unschedulable=bool(spec_doc.get("unschedulable", False)),
            taints=[Taint(key=t.get("key", ""), value=t.get("value", ""),
                          effect=t.get("effect", "NoSchedule"))
                    for t in spec_doc.get("taints") or []],
        ),
        status=NodeStatus(
            allocatable=dict(status_doc.get("allocatable") or {}),
            capacity=dict(status_doc.get("capacity") or {}),
        ),
    )


def decode_configmap(doc: Dict[str, Any]) -> ConfigMap:
    import base64

    binary = {}
    for k, v in (doc.get("binaryData") or {}).items():
        try:
            binary[k] = base64.b64decode(v)
        except Exception:
            continue
    return ConfigMap(
        metadata=_meta(doc),
        data=dict(doc.get("data") or {}),
        binary_data=binary,
    )


def decode_priority_class(doc: Dict[str, Any]) -> PriorityClass:
    return PriorityClass(
        metadata=_meta(doc),
        value=int(doc.get("value", 0) or 0),
        global_default=bool(doc.get("globalDefault", False)),
        preemption_policy=doc.get("preemptionPolicy", "") or "",
    )


def decode_namespace(doc: Dict[str, Any]) -> Namespace:
    return Namespace(metadata=_meta(doc))


def decode_resource_claim(doc: Dict[str, Any]) -> ResourceClaim:
    m = _meta(doc)
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    # structured parameters: one device request's class; allocation status
    # carries the node selector result
    device_class = ""
    reqs = ((spec.get("devices") or {}).get("requests")) or []
    if reqs:
        device_class = reqs[0].get("deviceClassName", "")
    allocated_node = ""
    alloc = status.get("allocation") or {}
    node_sel = (alloc.get("nodeSelector") or {}).get("nodeSelectorTerms") or []
    for term in node_sel:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("values"):
                allocated_node = f["values"][0]
    reserved = [r.get("uid", "") for r in status.get("reservedFor") or []]
    return ResourceClaim(name=m.name, namespace=m.namespace,
                         device_class=device_class,
                         allocated_node=allocated_node,
                         reserved_for=[r for r in reserved if r])


def decode_resource_slice(doc: Dict[str, Any]) -> ResourceSlice:
    spec = doc.get("spec") or {}
    devices = spec.get("devices") or []
    # one slice publishes devices of (usually) one class; count them
    cls = ""
    if devices:
        cls = (devices[0].get("basic") or {}).get("deviceClassName", "") or \
              devices[0].get("deviceClassName", "")
    if not cls:
        cls = spec.get("deviceClassName", "")
    return ResourceSlice(
        node_name=spec.get("nodeName", ""),
        device_class=cls,
        count=len(devices) or int(spec.get("count", 0) or 0),
    )


# --------------------------------------------------------------------------
# Volume kinds (PVC / PV / StorageClass / CSINode) — real-adapter coverage of
# the reference's volume informers (apifactory.go:39-59) and the shim-side
# binder's write path.
# --------------------------------------------------------------------------

def decode_pvc(doc: Dict[str, Any]) -> "PersistentVolumeClaim":
    from yunikorn_tpu.common.objects import PersistentVolumeClaim
    from yunikorn_tpu.common.resource import parse_quantity

    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    requested = 0
    res = ((spec.get("resources") or {}).get("requests")) or {}
    if "storage" in res:
        try:
            requested = parse_quantity(res["storage"])
        except ValueError:
            requested = 0
    volume_name = spec.get("volumeName", "") or ""
    phase = status.get("phase", "") or ""
    return PersistentVolumeClaim(
        metadata=_meta(doc),
        storage_class=spec.get("storageClassName", "") or "",
        bound=(phase == "Bound") or bool(volume_name and phase != "Lost"),
        volume_name=volume_name,
        requested_storage=requested,
        access_modes=list(spec.get("accessModes") or ["ReadWriteOnce"]),
        raw=doc,
    )


def encode_pvc(pvc) -> Dict[str, Any]:
    """PVC → API document.

    When the claim came from the API (raw present), merge the binder's
    mutations into a copy of the original document: a full-object PUT must
    keep volumeMode/selector/dataSource/resourceVersion or the real API
    server rejects it (immutable-spec validation / conflict detection).
    """
    if getattr(pvc, "raw", None):
        doc = copy.deepcopy(pvc.raw)
        meta = doc.setdefault("metadata", {})
        meta["annotations"] = dict(pvc.metadata.annotations)
        meta["labels"] = dict(pvc.metadata.labels)
        if pvc.volume_name:
            doc.setdefault("spec", {})["volumeName"] = pvc.volume_name
        if pvc.bound:
            doc.setdefault("status", {})["phase"] = "Bound"
        return doc
    doc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {
            "name": pvc.metadata.name,
            "namespace": pvc.metadata.namespace,
            "annotations": dict(pvc.metadata.annotations),
            "labels": dict(pvc.metadata.labels),
        },
        "spec": {
            "accessModes": list(pvc.access_modes),
            "storageClassName": pvc.storage_class,
        },
    }
    if pvc.requested_storage:
        doc["spec"]["resources"] = {"requests": {"storage": str(pvc.requested_storage)}}
    if pvc.volume_name:
        doc["spec"]["volumeName"] = pvc.volume_name
    if pvc.bound:
        doc["status"] = {"phase": "Bound"}
    return doc


def decode_pv(doc: Dict[str, Any]) -> "PersistentVolume":
    from yunikorn_tpu.common.objects import PersistentVolume
    from yunikorn_tpu.common.resource import parse_quantity

    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    capacity = 0
    cap = spec.get("capacity") or {}
    if "storage" in cap:
        try:
            capacity = parse_quantity(cap["storage"])
        except ValueError:
            capacity = 0
    claim_ref = ""
    cr = spec.get("claimRef") or {}
    if cr.get("name"):
        claim_ref = f"{cr.get('namespace', 'default')}/{cr['name']}"
    # simplified node affinity: flatten required matchExpressions with a
    # single In value into label equality (the common zonal-volume shape)
    node_affinity: Dict[str, str] = {}
    na = ((spec.get("nodeAffinity") or {}).get("required")) or {}
    for term in na.get("nodeSelectorTerms") or []:
        for e in term.get("matchExpressions") or []:
            vals = e.get("values") or []
            if e.get("operator") == "In" and len(vals) == 1:
                node_affinity[e.get("key", "")] = vals[0]
    return PersistentVolume(
        metadata=_meta(doc),
        capacity=capacity,
        access_modes=list(spec.get("accessModes") or ["ReadWriteOnce"]),
        storage_class=spec.get("storageClassName", "") or "",
        claim_ref=claim_ref,
        phase=status.get("phase", "Available") or "Available",
        node_affinity=node_affinity,
        raw=doc,
    )


def _claim_ref_doc(claim_ref: str) -> Dict[str, Any]:
    ns, name = claim_ref.split("/", 1)
    return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "namespace": ns, "name": name}


def encode_pv(pv) -> Dict[str, Any]:
    """PV → API document.

    When the volume came from the API (raw present), merge the binder's
    mutations (claimRef, phase) into a copy of the original document — PV
    validation requires exactly one volume source (csi/nfs/hostPath/...),
    which the simplified model does not carry, so a synthesized document
    would be rejected by a real API server.
    """
    if getattr(pv, "raw", None):
        doc = copy.deepcopy(pv.raw)
        if pv.claim_ref:
            doc.setdefault("spec", {})["claimRef"] = _claim_ref_doc(pv.claim_ref)
        doc.setdefault("status", {})["phase"] = pv.phase
        return doc
    doc = {
        "apiVersion": "v1",
        "kind": "PersistentVolume",
        "metadata": {"name": pv.metadata.name},
        "spec": {
            "capacity": {"storage": str(pv.capacity)},
            "accessModes": list(pv.access_modes),
            "storageClassName": pv.storage_class,
        },
        "status": {"phase": pv.phase},
    }
    if pv.claim_ref:
        doc["spec"]["claimRef"] = _claim_ref_doc(pv.claim_ref)
    if pv.node_affinity:
        doc["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [
                {"key": k, "operator": "In", "values": [v]}
                for k, v in pv.node_affinity.items()]}]}}
    return doc


def decode_storage_class(doc: Dict[str, Any]) -> "StorageClass":
    from yunikorn_tpu.common.objects import StorageClass

    return StorageClass(
        metadata=_meta(doc),
        provisioner=doc.get("provisioner", "") or "",
        volume_binding_mode=doc.get("volumeBindingMode", "Immediate") or "Immediate",
    )


def decode_csidriver(doc: Dict[str, Any]) -> "CSIDriverInfo":
    from yunikorn_tpu.common.objects import CSIDriverInfo

    spec = doc.get("spec") or {}
    return CSIDriverInfo(
        metadata=_meta(doc),
        attach_required=bool(spec.get("attachRequired", True)),
        storage_capacity=bool(spec.get("storageCapacity", False)),
    )


def decode_csistoragecapacity(doc: Dict[str, Any]) -> "CSIStorageCapacityInfo":
    from yunikorn_tpu.common.objects import CSIStorageCapacityInfo
    from yunikorn_tpu.common.resource import parse_quantity

    def qty(key: str) -> int:
        raw = doc.get(key)
        if not raw:
            return 0
        try:
            return parse_quantity(raw)
        except ValueError:
            return 0

    topo: Dict[str, str] = {}
    nt = doc.get("nodeTopology")
    # upstream: a NIL selector matches NO nodes (labels.Nothing()); only a
    # present-but-empty selector matches everything
    unsupported = nt is None
    nt = nt or {}
    topo.update(nt.get("matchLabels") or {})
    for e in nt.get("matchExpressions") or []:
        vals = e.get("values") or []
        if e.get("operator") == "In" and len(vals) == 1:
            topo[e.get("key", "")] = vals[0]
        else:
            # can't represent it exactly → the segment fails closed
            unsupported = True
    return CSIStorageCapacityInfo(
        metadata=_meta(doc),
        storage_class=doc.get("storageClassName", "") or "",
        node_topology=topo,
        capacity=qty("capacity"),
        maximum_volume_size=qty("maximumVolumeSize"),
        topology_unsupported=unsupported,
    )


def decode_volumeattachment(doc: Dict[str, Any]) -> "VolumeAttachmentInfo":
    from yunikorn_tpu.common.objects import VolumeAttachmentInfo

    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return VolumeAttachmentInfo(
        metadata=_meta(doc),
        attacher=spec.get("attacher", "") or "",
        node_name=spec.get("nodeName", "") or "",
        pv_name=((spec.get("source") or {}).get("persistentVolumeName")) or "",
        attached=bool(status.get("attached", False)),
    )


def decode_csinode(doc: Dict[str, Any]) -> "CSINodeInfo":
    from yunikorn_tpu.common.objects import CSINodeInfo

    spec = doc.get("spec") or {}
    limits: Dict[str, int] = {}
    for drv in spec.get("drivers") or []:
        count = ((drv.get("allocatable") or {}).get("count"))
        if count is not None:
            limits[drv.get("name", "")] = int(count)
    return CSINodeInfo(metadata=_meta(doc), driver_limits=limits)
