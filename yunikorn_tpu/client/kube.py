"""Real-Kubernetes adapter: kubeconfig-speaking KubeClient + APIProvider.

Role-equivalent to pkg/client/kubeclient.go (Bind via the pods/binding
subresource, :111-134) and pkg/client/apifactory.go:92-165 (informers via
list+watch). Implemented on the standard library (http.client + ssl): the
image ships no kubernetes-python package, and the surface the shim needs —
GET/LIST/WATCH a handful of resource types, POST bindings/pods, PATCH status
— is small. QPS/burst limiting matches the reference defaults
(schedulerconf.go:94-95, 1000/1000) with a token bucket.

Watches use the streaming JSON protocol: one JSON object per line, `type` in
ADDED/MODIFIED/DELETED/BOOKMARK/ERROR, resuming from the last
resourceVersion; a 410 Gone falls back to a fresh LIST (client-go reflector
behavior).
"""
from __future__ import annotations

import base64
import http.client
import json
import os
import random
import ssl
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

import yaml

from yunikorn_tpu.client import k8s_codec as codec
from yunikorn_tpu.client.interfaces import (
    APIProvider,
    InformerType,
    KubeClient,
    ResourceEventHandlers,
)
from yunikorn_tpu.common.objects import ConfigMap, Node, Pod, PriorityClass
from yunikorn_tpu.locking import locking
from yunikorn_tpu.log.logger import log

logger = log("shim.client")

# resource type → (URL path prefix, decoder); core/v1 unless noted
_RESOURCES: Dict[InformerType, Tuple[str, Callable]] = {
    InformerType.POD: ("/api/v1/pods", codec.decode_pod),
    InformerType.NODE: ("/api/v1/nodes", codec.decode_node),
    InformerType.CONFIGMAP: ("/api/v1/configmaps", codec.decode_configmap),
    InformerType.PRIORITY_CLASS: (
        "/apis/scheduling.k8s.io/v1/priorityclasses", codec.decode_priority_class),
    InformerType.NAMESPACE: ("/api/v1/namespaces", codec.decode_namespace),
    InformerType.RESOURCE_CLAIM: (
        "/apis/resource.k8s.io/v1beta1/resourceclaims", codec.decode_resource_claim),
    InformerType.RESOURCE_SLICE: (
        "/apis/resource.k8s.io/v1beta1/resourceslices", codec.decode_resource_slice),
    # volume informers (reference apifactory.go:39-59: PV/PVC/StorageClass/
    # CSINode feed the volume binder and per-node attach limits)
    InformerType.PVC: ("/api/v1/persistentvolumeclaims", codec.decode_pvc),
    InformerType.PV: ("/api/v1/persistentvolumes", codec.decode_pv),
    InformerType.STORAGE_CLASS: (
        "/apis/storage.k8s.io/v1/storageclasses", codec.decode_storage_class),
    InformerType.CSINODE: ("/apis/storage.k8s.io/v1/csinodes", codec.decode_csinode),
    InformerType.CSI_DRIVER: (
        "/apis/storage.k8s.io/v1/csidrivers", codec.decode_csidriver),
    InformerType.CSI_STORAGE_CAPACITY: (
        "/apis/storage.k8s.io/v1/csistoragecapacities",
        codec.decode_csistoragecapacity),
    InformerType.VOLUME_ATTACHMENT: (
        "/apis/storage.k8s.io/v1/volumeattachments",
        codec.decode_volumeattachment),
}


class KubeConfig:
    """Minimal kubeconfig loader: current-context server + auth material."""

    def __init__(self, server: str, ssl_context: ssl.SSLContext,
                 token: str = ""):
        self.server = server.rstrip("/")
        self.ssl_context = ssl_context
        self.token = token

    @classmethod
    def load(cls, path: Optional[str] = None) -> "KubeConfig":
        path = path or os.environ.get("KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        ctx_name = doc.get("current-context", "")
        ctx = next((c["context"] for c in doc.get("contexts", [])
                    if c.get("name") == ctx_name), None)
        if ctx is None:
            raise ValueError(f"kubeconfig {path}: current-context {ctx_name!r} not found")
        cluster = next((c["cluster"] for c in doc.get("clusters", [])
                        if c.get("name") == ctx.get("cluster")), {})
        user = next((u["user"] for u in doc.get("users", [])
                     if u.get("name") == ctx.get("user")), {})
        server = cluster.get("server", "https://127.0.0.1:6443")

        sctx = ssl.create_default_context()
        ca_data = cluster.get("certificate-authority-data")
        ca_file = cluster.get("certificate-authority")
        if ca_data:
            sctx.load_verify_locations(cadata=base64.b64decode(ca_data).decode())
        elif ca_file:
            sctx.load_verify_locations(cafile=ca_file)
        elif cluster.get("insecure-skip-tls-verify"):
            sctx.check_hostname = False
            sctx.verify_mode = ssl.CERT_NONE

        cert_data = user.get("client-certificate-data")
        key_data = user.get("client-key-data")
        cert_file = user.get("client-certificate")
        key_file = user.get("client-key")
        if cert_data and key_data:
            # ssl needs files; write to a private tmpdir that lives as long
            # as the process (the reference reads cert files from disk too)
            d = tempfile.mkdtemp(prefix="yk-kubecfg-")
            cert_file = os.path.join(d, "client.crt")
            key_file = os.path.join(d, "client.key")
            with open(cert_file, "wb") as f:
                f.write(base64.b64decode(cert_data))
            with open(key_file, "wb") as f:
                f.write(base64.b64decode(key_data))
            os.chmod(key_file, 0o600)
        if cert_file and key_file:
            sctx.load_cert_chain(cert_file, key_file)
        token = user.get("token", "")
        return cls(server, sctx, token)


class _TokenBucket:
    """QPS/burst limiter (reference kube QPS/Burst, schedulerconf.go:94-95)."""

    def __init__(self, qps: float, burst: int):
        self.qps = max(float(qps), 0.001)
        self.burst = max(int(burst), 1)
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = locking.Mutex()

    def take(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class RealKubeClient(KubeClient):
    """HTTP mutations against the API server."""

    def __init__(self, config: KubeConfig, qps: int = 1000, burst: int = 1000):
        self.config = config
        self._bucket = _TokenBucket(qps, burst)

    # -- low-level ----------------------------------------------------------
    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json",
                 timeout: float = 30.0):
        self._bucket.take()
        url = self.config.server + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        return urllib.request.urlopen(req, context=self.config.ssl_context,
                                      timeout=timeout)

    # transient transport failures worth a bounded retry: connection-level
    # errors where no HTTP status ever arrived (apiserver restart, LB blip,
    # accept-queue shed). HTTP errors are NOT retried here — the caller owns
    # status semantics (e.g. bind() treating 409 as already-bound).
    RETRY_STEPS = 3
    _TRANSIENT = (ConnectionResetError, ConnectionRefusedError,
                  BrokenPipeError, http.client.RemoteDisconnected,
                  TimeoutError)

    def request_json(self, method: str, path: str, body: Optional[dict] = None,
                     content_type: str = "application/json") -> dict:
        for attempt in range(self.RETRY_STEPS + 1):
            try:
                with self._request(method, path, body, content_type) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError:
                raise
            except urllib.error.URLError as e:
                if (attempt >= self.RETRY_STEPS
                        or not isinstance(e.reason, self._TRANSIENT)):
                    raise
            except self._TRANSIENT:
                if attempt >= self.RETRY_STEPS:
                    raise
            time.sleep(0.1 * (2 ** attempt) + random.uniform(0, 0.05))

    # -- KubeClient ---------------------------------------------------------
    def bind(self, pod: Pod, node_name: str) -> None:
        """pods/binding subresource (reference kubeclient.go:111-134).

        409 Conflict means the pod is already assigned — either our own
        retried POST whose first attempt landed before the connection died,
        or a genuine race; the task's Bound/informer path reconciles both."""
        try:
            self.request_json(
                "POST",
                f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/binding",
                {
                    "apiVersion": "v1",
                    "kind": "Binding",
                    "metadata": {"name": pod.name, "uid": pod.uid},
                    "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
                },
            )
        except urllib.error.HTTPError as e:
            if e.code != 409:
                raise
            # already assigned: success only if it is assigned to OUR node
            doc = self.request_json(
                "GET", f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}")
            assigned = ((doc.get("spec") or {}).get("nodeName")) or ""
            if assigned != node_name:
                raise

    def create(self, pod: Pod) -> Pod:
        doc = self.request_json(
            "POST", f"/api/v1/namespaces/{pod.namespace}/pods", codec.encode_pod(pod))
        return codec.decode_pod(doc)

    def delete(self, pod: Pod) -> None:
        try:
            self.request_json(
                "DELETE", f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def update_pod_condition(self, pod: Pod, condition) -> bool:
        self.request_json(
            "PATCH",
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/status",
            {"status": {"conditions": [{
                "type": condition.type, "status": condition.status,
                "reason": condition.reason, "message": condition.message,
            }]}},
            content_type="application/strategic-merge-patch+json",
        )
        return True

    def update_pvc(self, pvc) -> None:
        """Replace a claim: the binder writes volumeName / the
        selected-node annotation (volume binding write path)."""
        self.request_json(
            "PUT",
            f"/api/v1/namespaces/{pvc.metadata.namespace}"
            f"/persistentvolumeclaims/{pvc.metadata.name}",
            codec.encode_pvc(pvc))

    def update_pv(self, pv) -> None:
        """Replace a PV: the binder sets claimRef on static binds."""
        self.request_json(
            "PUT", f"/api/v1/persistentvolumes/{pv.metadata.name}",
            codec.encode_pv(pv))

    def get_configmap(self, namespace: str, name: str) -> Optional[ConfigMap]:
        try:
            doc = self.request_json(
                "GET", f"/api/v1/namespaces/{namespace}/configmaps/{name}")
            return codec.decode_configmap(doc)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise


class _Informer:
    """One resource type's reflector: LIST, then WATCH with resume/relist."""

    def __init__(self, client: RealKubeClient, informer: InformerType,
                 namespace: str = ""):
        self.client = client
        self.informer = informer
        path, decoder = _RESOURCES[informer]
        self.path = path
        self.decoder = decoder
        self.namespace = namespace
        self.handlers: List[ResourceEventHandlers] = []
        self.store: Dict[str, object] = {}          # uid/name -> object
        # guards store mutation vs snapshot readers (list_pods/list_nodes run
        # on other threads while the informer thread applies watch events)
        self._store_lock = threading.Lock()
        self.synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # reflector health: restart count + last successful sync activity
        # (relist done or watch event applied). Exported through
        # attach_metrics / RealAPIProvider.sync_ages so restarts and
        # staleness are visible instead of only warned into the log.
        self.restarts = 0
        self.last_sync: Optional[float] = None
        self._m_restarts = None
        self._g_sync_age = None

    def attach_metrics(self, registry) -> None:
        first = self._g_sync_age is None
        self._m_restarts = registry.counter(
            "informer_restarts_total",
            "reflector loop restarts after an error, by informer",
            labelnames=("informer",))
        self._g_sync_age = registry.gauge(
            "informer_last_sync_age_seconds",
            "seconds since the informer last made sync progress "
            "(refreshed at each scrape, each sync and each health probe)",
            labelnames=("informer",))
        if self.restarts:
            self._m_restarts.inc(self.restarts, informer=self.informer.value)
        if first:
            # gauges are push-model: without a per-scrape refresh, a wedged
            # informer's age would stay frozen at its last pushed value for
            # deployments that only scrape /metrics and never hit the
            # health endpoint — flat 0 during exactly the staleness
            # incident the gauge exists to surface
            registry.on_collect(self.sync_age)

    def _note_sync(self) -> None:
        # timestamp only: the on_collect hook re-derives the gauge at each
        # scrape, so the per-event push would just be metric-lock traffic
        # on the reflector hot path
        self.last_sync = time.time()

    def sync_age(self) -> Optional[float]:
        """Seconds since last sync progress; None = never synced. Refreshes
        the exported gauge as a side effect (gauges are push-model)."""
        age = None if self.last_sync is None else time.time() - self.last_sync
        if age is not None and self._g_sync_age is not None:
            self._g_sync_age.set(round(age, 3), informer=self.informer.value)
        return age

    def _key(self, obj) -> str:
        uid = getattr(getattr(obj, "metadata", None), "uid", "")
        return uid or getattr(obj, "key", "") or getattr(obj, "name", "")

    def snapshot(self) -> List[object]:
        with self._store_lock:
            return list(self.store.values())

    def _deliver(self, kind: str, obj, old=None) -> None:
        for h in self.handlers:
            try:
                if h.filter_fn is not None and not h.filter_fn(obj):
                    continue
                if kind == "add" and h.add_fn:
                    h.add_fn(obj)
                elif kind == "update" and h.update_fn:
                    h.update_fn(old if old is not None else obj, obj)
                elif kind == "delete" and h.delete_fn:
                    h.delete_fn(obj)
            except Exception:
                logger.exception("%s handler failed for %s event", self.informer, kind)

    def _list_path(self, watch: bool, rv: str = "") -> str:
        path = self.path
        if self.namespace:
            # namespace-scoped listing (e.g. configmaps under RBAC that only
            # grants the yunikorn namespace): /api/v1/namespaces/{ns}/<kind>
            prefix, kind = path.rsplit("/", 1)
            path = f"{prefix}/namespaces/{self.namespace}/{kind}"
        q = {"watch": "true"} if watch else {}
        if rv:
            q["resourceVersion"] = rv
            q["allowWatchBookmarks"] = "true"
        qs = ("?" + urllib.parse.urlencode(q)) if q else ""
        return path + qs

    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"informer-{self.informer.value}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    _BACKOFF_BASE = 0.5
    _BACKOFF_MAX = 30.0

    def _loop(self) -> None:
        import random

        rv = ""
        backoff = self._BACKOFF_BASE
        while not self._stop.is_set():
            try:
                if not rv:
                    rv = self._relist()
                    backoff = self._BACKOFF_BASE  # healthy again
                # returns the resume resourceVersion on a clean stream end
                # (idle timeout), "" on 410 Gone → relist (client-go reflector)
                rv = self._watch(rv)
            except TimeoutError:
                continue  # idle watch socket; resume from the same rv
            except Exception as e:
                # exponential backoff with full jitter (client-go reflector
                # backs off the same way); a flapping API server must not be
                # hammered at a fixed 1 Hz by every informer at once. The
                # backoff CAPS at _BACKOFF_MAX: recovery latency after a
                # long outage stays bounded (pinned by test_kube_chaos).
                delay = backoff * (0.5 + random.random())
                backoff = min(backoff * 2.0, self._BACKOFF_MAX)
                self.restarts += 1
                if self._m_restarts is not None:
                    self._m_restarts.inc(informer=self.informer.value)
                logger.warning("informer %s restarting after error (backoff %.1fs): %s",
                               self.informer.value, delay, e)
                rv = ""
                if self._stop.wait(delay):
                    return

    def _relist(self) -> str:
        doc = self.client.request_json("GET", self._list_path(False))
        rv = (doc.get("metadata") or {}).get("resourceVersion", "")
        fresh: Dict[str, object] = {}
        for item in doc.get("items") or []:
            obj = self.decoder(item)
            fresh[self._key(obj)] = obj
        with self._store_lock:
            old = self.store
            self.store = fresh
        for key, obj in fresh.items():
            if key in old:
                self._deliver("update", obj, old[key])
            else:
                self._deliver("add", obj)
        for key, obj in old.items():
            if key not in fresh:
                self._deliver("delete", obj)
        self.synced.set()
        self._note_sync()
        return rv

    def _watch(self, rv: str) -> str:
        """Stream events, tracking the resume resourceVersion. Returns the rv
        to reconnect with, or "" when the server signalled 410 Gone."""
        last_rv = rv
        with self.client._request("GET", self._list_path(True, rv),
                                  timeout=300.0) as resp:
            for line in resp:
                if self._stop.is_set():
                    return last_rv
                if not line.strip():
                    continue
                event = json.loads(line)
                etype = event.get("type", "")
                obj_doc = event.get("object") or {}
                if etype == "ERROR":
                    if obj_doc.get("code") == 410:  # Gone: resume window lost
                        logger.info("informer %s: 410 Gone, relisting",
                                    self.informer.value)
                        return ""
                    raise RuntimeError(f"watch error: {obj_doc}")
                last_rv = ((obj_doc.get("metadata") or {})
                           .get("resourceVersion") or last_rv)
                self._note_sync()
                if etype == "BOOKMARK":
                    continue
                obj = self.decoder(obj_doc)
                key = self._key(obj)
                if etype in ("ADDED", "MODIFIED"):
                    with self._store_lock:
                        old = self.store.get(key)
                        self.store[key] = obj
                    self._deliver("update" if old is not None else "add", obj, old)
                elif etype == "DELETED":
                    with self._store_lock:
                        self.store.pop(key, None)
                    self._deliver("delete", obj)
        return last_rv


class RealAPIProvider(APIProvider):
    """Informer factory against a live API server (apifactory.go:92-165)."""

    def __init__(self, config: KubeConfig, qps: int = 1000, burst: int = 1000,
                 enable_dra: bool = False, namespace: str = ""):
        self.config = config
        self.client = RealKubeClient(config, qps=qps, burst=burst)
        types = [InformerType.POD, InformerType.NODE, InformerType.CONFIGMAP,
                 InformerType.PRIORITY_CLASS, InformerType.NAMESPACE,
                 InformerType.PVC, InformerType.PV,
                 InformerType.STORAGE_CLASS, InformerType.CSINODE,
                 InformerType.CSI_DRIVER, InformerType.CSI_STORAGE_CAPACITY,
                 InformerType.VOLUME_ATTACHMENT]
        if enable_dra:
            types += [InformerType.RESOURCE_CLAIM, InformerType.RESOURCE_SLICE]
        self._informers: Dict[InformerType, _Informer] = {
            # the configmap informer is namespace-scoped (yunikorn's own
            # configmaps; RBAC typically only grants that namespace)
            t: _Informer(self.client, t,
                         namespace=namespace if t == InformerType.CONFIGMAP else "")
            for t in types
        }
        self._started = False

    # -- observability / health --------------------------------------------
    def attach_metrics(self, registry) -> None:
        """Wire reflector restart counters + last-sync-age gauges into the
        core's registry (the shim attaches this next to the dispatcher's)."""
        for inf in self._informers.values():
            inf.attach_metrics(registry)

    def sync_ages(self) -> Dict[str, Optional[float]]:
        """{informer: seconds since last sync progress} (None = never) —
        the staleness input of robustness/health.informers_source."""
        return {t.value: inf.sync_age() for t, inf in self._informers.items()}

    def restart_count(self) -> int:
        return sum(inf.restarts for inf in self._informers.values())

    # -- APIProvider --------------------------------------------------------
    def add_event_handler(self, informer: InformerType,
                          handlers: ResourceEventHandlers) -> None:
        inf = self._informers.get(informer)
        if inf is None:
            logger.debug("no real informer for %s; handler ignored", informer)
            return
        inf.handlers.append(handlers)
        if self._started and inf.synced.is_set():
            # late registration replays the store (client-go semantics)
            for obj in inf.snapshot():
                if handlers.filter_fn is not None and not handlers.filter_fn(obj):
                    continue
                if handlers.add_fn:
                    handlers.add_fn(obj)

    def get_client(self) -> KubeClient:
        return self.client

    def start(self) -> None:
        self._started = True
        for inf in self._informers.values():
            inf.run()

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_sync(self, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        for inf in self._informers.values():
            remaining = max(0.1, deadline - time.time())
            if not inf.synced.wait(timeout=remaining):
                raise TimeoutError(
                    f"informer {inf.informer.value} did not sync in {timeout}s")

    def get_pvc(self, namespace: str, name: str):
        """Claim lookup from the PVC informer store (volume-binder fallback
        when its own cache hasn't seen the claim yet)."""
        inf = self._informers.get(InformerType.PVC)
        if inf is None:
            return None
        for pvc in inf.snapshot():
            if (pvc.metadata.namespace == namespace
                    and pvc.metadata.name == name):
                return pvc
        return None

    def list_pods(self) -> List[Pod]:
        return self._informers[InformerType.POD].snapshot()

    def list_nodes(self) -> List[Node]:
        return self._informers[InformerType.NODE].snapshot()

    def list_priority_classes(self) -> List[PriorityClass]:
        return self._informers[InformerType.PRIORITY_CLASS].snapshot()


def load_bootstrap_configmaps(client: RealKubeClient, namespace: str):
    """yunikorn-defaults + yunikorn-configs read BEFORE informers exist
    (reference client/bootstrap.go:28). Returns (maps, binary_maps) aligned
    lists — binaryData carries gzip-compressed config values
    (schedulerconf Decompress support)."""
    maps: List[Optional[dict]] = []
    binary_maps: List[dict] = []
    for name in ("yunikorn-defaults", "yunikorn-configs"):
        cm = client.get_configmap(namespace, name)
        maps.append(dict(cm.data) if cm is not None else None)
        binary_maps.append(dict(cm.binary_data) if cm is not None else {})
    return maps, binary_maps
