"""FakeCluster: an in-memory cluster with informer semantics.

This is the framework's MockedAPIProvider + KubeClientMock analog (reference
pkg/client/apifactory_mock.go:42-599, kubeclient_mock.go:36-235) and, scaled up,
its kwok-style perf harness (reference deployments/kwok-perf-test). It holds the
object store (pods/nodes/configmaps/priorityclasses), fans events out to
registered handlers (synchronously, like client-go informers on a single informer
goroutine), executes binds by mutating the store and re-firing update events, and
records BindStats (first/last bind time + count) for throughput measurement
(reference kubeclient_mock.go:51-64, used by scheduler_perf_test.go:138-142).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.client.interfaces import (
    APIProvider,
    InformerType,
    KubeClient,
    ResourceEventHandlers,
)
from yunikorn_tpu.common.objects import (
    ConfigMap,
    Namespace,
    Node,
    PersistentVolumeClaim,
    Pod,
    PodCondition,
    PriorityClass,
)
from yunikorn_tpu.log.logger import log

logger = log("shim.client")


@dataclasses.dataclass
class BindStats:
    first_bind_time: Optional[float] = None
    last_bind_time: Optional[float] = None
    success_count: int = 0
    fail_count: int = 0

    def throughput(self) -> float:
        """Binds per second over the observed window (reference perf metric)."""
        if not self.success_count or self.first_bind_time is None:
            return 0.0
        span = (self.last_bind_time or 0) - self.first_bind_time
        if span <= 0:
            return float(self.success_count)
        return self.success_count / span


class FakeKubeClient(KubeClient):
    def __init__(self, cluster: "FakeCluster"):
        self._cluster = cluster
        self.bind_stats = BindStats()
        self.bind_fn = None      # test hook: override bind behavior
        self.create_fn = None
        self.delete_fn = None
        self._lock = locking.Mutex()

    def update_pvc(self, pvc) -> None:
        self._cluster.update_pvc(pvc)

    def update_pv(self, pv) -> None:
        self._cluster.update_pv(pv)

    def bind(self, pod: Pod, node_name: str) -> None:
        try:
            if self.bind_fn is not None:
                self.bind_fn(pod, node_name)
            else:
                self._cluster.bind_pod(pod.uid, node_name)
        except Exception:
            with self._lock:
                self.bind_stats.fail_count += 1
            raise
        now = time.time()
        with self._lock:
            if self.bind_stats.first_bind_time is None:
                self.bind_stats.first_bind_time = now
            self.bind_stats.last_bind_time = now
            self.bind_stats.success_count += 1

    def create(self, pod: Pod) -> Pod:
        if self.create_fn is not None:
            return self.create_fn(pod)
        return self._cluster.add_pod(pod)

    def delete(self, pod: Pod) -> None:
        if self.delete_fn is not None:
            self.delete_fn(pod)
            return
        self._cluster.delete_pod(pod.uid)

    def update_pod_condition(self, pod: Pod, condition: PodCondition) -> bool:
        # dedup identical conditions (reference task.go:577-597)
        for existing in pod.status.conditions:
            if (existing.type == condition.type and existing.status == condition.status
                    and existing.reason == condition.reason and existing.message == condition.message):
                return False
        pod.status.conditions = [c for c in pod.status.conditions if c.type != condition.type]
        pod.status.conditions.append(condition)
        return True

    def get_configmap(self, namespace: str, name: str) -> Optional[ConfigMap]:
        return self._cluster.get_configmap(namespace, name)


class FakeCluster(APIProvider):
    """In-memory cluster: object store + synchronous informer fan-out."""

    def __init__(self):
        self._lock = locking.RMutex()
        self._pods: Dict[str, Pod] = {}
        self._nodes: Dict[str, Node] = {}
        self._configmaps: Dict[str, ConfigMap] = {}
        self._priority_classes: Dict[str, PriorityClass] = {}
        self._pvcs: Dict[str, PersistentVolumeClaim] = {}
        self._pvs: Dict[str, object] = {}
        self._storage_classes: Dict[str, object] = {}
        self._csinodes: Dict[str, object] = {}
        self._csi_drivers: Dict[str, object] = {}
        self._csi_capacities: Dict[str, object] = {}
        self._volume_attachments: Dict[str, object] = {}
        # built-in provisioner sim: see update_pvc
        self.auto_provision = True
        self._namespaces: Dict[str, Namespace] = {}
        self._handlers: Dict[InformerType, List[ResourceEventHandlers]] = {}
        self._client = FakeKubeClient(self)
        self._started = False

    # ------------------------------------------------------------ APIProvider
    def add_event_handler(self, informer: InformerType, handlers: ResourceEventHandlers) -> None:
        with self._lock:
            self._handlers.setdefault(informer, []).append(handlers)
            # late registration replays adds, like informer cache sync
            if self._started:
                for obj in self._objects_of(informer):
                    self._fire_one(handlers, "add", obj)

    def get_client(self) -> FakeKubeClient:
        return self._client

    def start(self) -> None:
        with self._lock:
            self._started = True
            # replay existing objects to all handlers (informer initial sync)
            for informer, hs in self._handlers.items():
                for obj in self._objects_of(informer):
                    for h in hs:
                        self._fire_one(h, "add", obj)

    def stop(self) -> None:
        self._started = False

    def clear_event_handlers(self) -> None:
        """Drop every registered informer handler: a restarting scheduler's
        watch connections die with its process while the API-server state
        persists. The next shim re-registers and gets the standard initial
        sync replay (add_event_handler late-registration path)."""
        with self._lock:
            self._handlers.clear()

    def wait_for_sync(self) -> None:
        return  # synchronous fan-out: always in sync

    def list_pods(self) -> List[Pod]:
        with self._lock:
            return list(self._pods.values())

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def list_priority_classes(self) -> List[PriorityClass]:
        with self._lock:
            return list(self._priority_classes.values())

    # ------------------------------------------------------------ object CRUD
    def add_pod(self, pod: Pod) -> Pod:
        with self._lock:
            self._pods[pod.uid] = pod
        self._fire(InformerType.POD, "add", pod)
        return pod

    def update_pod(self, pod: Pod, old: Optional[Pod] = None) -> None:
        with self._lock:
            prev = old if old is not None else self._pods.get(pod.uid, pod)
            self._pods[pod.uid] = pod
        self._fire(InformerType.POD, "update", pod, prev)

    def delete_pod(self, uid: str) -> None:
        with self._lock:
            pod = self._pods.pop(uid, None)
        if pod is not None:
            self._fire(InformerType.POD, "delete", pod)

    def get_pod(self, uid: str) -> Optional[Pod]:
        with self._lock:
            return self._pods.get(uid)

    def bind_pod(self, uid: str, node_name: str) -> None:
        """Execute a bind: set nodeName + phase Running, fire an update event."""
        with self._lock:
            pod = self._pods.get(uid)
            if pod is None:
                raise KeyError(f"bind: pod {uid} not found")
            if node_name not in self._nodes:
                raise KeyError(f"bind: node {node_name} not found")
            old = pod.deepcopy()
            pod.spec.node_name = node_name
            pod.status.phase = "Running"
        self._fire(InformerType.POD, "update", pod, old)

    def succeed_pod(self, uid: str) -> None:
        with self._lock:
            pod = self._pods.get(uid)
            if pod is None:
                return
            old = pod.deepcopy()
            pod.status.phase = "Succeeded"
        self._fire(InformerType.POD, "update", pod, old)

    def fail_pod(self, uid: str, reason: str = "Error") -> None:
        with self._lock:
            pod = self._pods.get(uid)
            if pod is None:
                return
            old = pod.deepcopy()
            pod.status.phase = "Failed"
            pod.status.reason = reason
        self._fire(InformerType.POD, "update", pod, old)

    def add_resource_claim(self, claim) -> None:
        self._fire(InformerType.RESOURCE_CLAIM, "add", claim)

    def add_resource_slice(self, sl) -> None:
        self._fire(InformerType.RESOURCE_SLICE, "add", sl)

    def add_node(self, node: Node) -> Node:
        with self._lock:
            self._nodes[node.name] = node
        self._fire(InformerType.NODE, "add", node)
        return node

    def update_node(self, node: Node) -> None:
        with self._lock:
            old = self._nodes.get(node.name, node)
            self._nodes[node.name] = node
        self._fire(InformerType.NODE, "update", node, old)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
        if node is not None:
            self._fire(InformerType.NODE, "delete", node)

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(name)

    def add_configmap(self, cm: ConfigMap) -> None:
        with self._lock:
            old = self._configmaps.get(f"{cm.metadata.namespace}/{cm.metadata.name}")
            self._configmaps[f"{cm.metadata.namespace}/{cm.metadata.name}"] = cm
        self._fire(InformerType.CONFIGMAP, "update" if old else "add", cm, old)

    def get_configmap(self, namespace: str, name: str) -> Optional[ConfigMap]:
        with self._lock:
            return self._configmaps.get(f"{namespace}/{name}")

    def add_namespace(self, ns: Namespace) -> None:
        with self._lock:
            self._namespaces[ns.metadata.name] = ns
        self._fire(InformerType.NAMESPACE, "add", ns)

    def get_namespace(self, name: str) -> Optional[Namespace]:
        with self._lock:
            return self._namespaces.get(name)

    def add_pvc(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            self._pvcs[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc
        self._fire(InformerType.PVC, "add", pvc)

    def get_pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            return self._pvcs.get(f"{namespace}/{name}")

    def delete_pvc(self, namespace: str, name: str) -> None:
        with self._lock:
            pvc = self._pvcs.pop(f"{namespace}/{name}", None)
        if pvc is not None:
            self._fire(InformerType.PVC, "delete", pvc)

    def bind_pvc(self, namespace: str, name: str, volume_name: str = "") -> None:
        with self._lock:
            pvc = self._pvcs.get(f"{namespace}/{name}")
            if pvc is None:
                raise KeyError(f"pvc {namespace}/{name} not found")
            pvc.bound = True
            pvc.volume_name = volume_name or f"pv-{name}"
        self._fire(InformerType.PVC, "update", pvc, pvc)

    # ---------------------------------------------------- volumes (PV/SC/CSI)
    def add_pv(self, pv) -> None:
        with self._lock:
            self._pvs[pv.metadata.name] = pv
        self._fire(InformerType.PV, "add", pv)

    def get_pv(self, name: str):
        with self._lock:
            return self._pvs.get(name)

    def update_pv(self, pv) -> None:
        with self._lock:
            self._pvs[pv.metadata.name] = pv
        self._fire(InformerType.PV, "update", pv, pv)

    def add_storage_class(self, sc) -> None:
        with self._lock:
            self._storage_classes[sc.metadata.name] = sc
        self._fire(InformerType.STORAGE_CLASS, "add", sc)

    def add_csinode(self, csinode) -> None:
        with self._lock:
            self._csinodes[csinode.metadata.name] = csinode
        self._fire(InformerType.CSINODE, "add", csinode)

    def add_csi_driver(self, drv) -> None:
        with self._lock:
            self._csi_drivers[drv.metadata.name] = drv
        self._fire(InformerType.CSI_DRIVER, "add", drv)

    def add_csi_capacity(self, cap) -> None:
        with self._lock:
            key = f"{cap.metadata.namespace}/{cap.metadata.name}"
            self._csi_capacities[key] = cap
        self._fire(InformerType.CSI_STORAGE_CAPACITY, "add", cap)

    def add_volume_attachment(self, va) -> None:
        with self._lock:
            self._volume_attachments[va.metadata.name] = va
        self._fire(InformerType.VOLUME_ATTACHMENT, "add", va)

    def delete_volume_attachment(self, name: str) -> None:
        with self._lock:
            va = self._volume_attachments.pop(name, None)
        if va is not None:
            self._fire(InformerType.VOLUME_ATTACHMENT, "delete", va)

    def update_pvc(self, pvc) -> None:
        """Replace a claim (binder writes volumeName/bound/annotations).

        The fake cluster doubles as the external provisioner (auto_provision,
        default on): an unbound claim carrying the
        volume.kubernetes.io/selected-node annotation gets bound immediately,
        like a CSI provisioner acting on the scheduler's node decision. Tests
        exercising real WaitForFirstConsumer latency set auto_provision=False
        and bind the claim themselves."""
        if (self.auto_provision and not pvc.bound
                and pvc.metadata.annotations.get("volume.kubernetes.io/selected-node")):
            pvc.bound = True
            pvc.volume_name = pvc.volume_name or f"pv-{pvc.metadata.name}"
        with self._lock:
            self._pvcs[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc
        self._fire(InformerType.PVC, "update", pvc, pvc)

    def add_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self._priority_classes[pc.name] = pc
        self._fire(InformerType.PRIORITY_CLASS, "add", pc)

    def delete_priority_class(self, name: str) -> None:
        with self._lock:
            pc = self._priority_classes.pop(name, None)
        if pc is not None:
            self._fire(InformerType.PRIORITY_CLASS, "delete", pc)

    # ----------------------------------------------------------------- events
    def _objects_of(self, informer: InformerType) -> List[object]:
        if informer == InformerType.POD:
            return list(self._pods.values())
        if informer == InformerType.NODE:
            return list(self._nodes.values())
        if informer == InformerType.CONFIGMAP:
            return list(self._configmaps.values())
        if informer == InformerType.PRIORITY_CLASS:
            return list(self._priority_classes.values())
        if informer == InformerType.PVC:
            return list(self._pvcs.values())
        if informer == InformerType.NAMESPACE:
            return list(self._namespaces.values())
        if informer == InformerType.PV:
            return list(self._pvs.values())
        if informer == InformerType.STORAGE_CLASS:
            return list(self._storage_classes.values())
        if informer == InformerType.CSINODE:
            return list(self._csinodes.values())
        if informer == InformerType.CSI_DRIVER:
            return list(self._csi_drivers.values())
        if informer == InformerType.CSI_STORAGE_CAPACITY:
            return list(self._csi_capacities.values())
        if informer == InformerType.VOLUME_ATTACHMENT:
            return list(self._volume_attachments.values())
        return []

    def _fire(self, informer: InformerType, kind: str, obj, old=None) -> None:
        with self._lock:
            handlers = list(self._handlers.get(informer, ()))
            started = self._started
        if not started:
            return
        for h in handlers:
            self._fire_one(h, kind, obj, old)

    @staticmethod
    def _fire_one(h: ResourceEventHandlers, kind: str, obj, old=None) -> None:
        try:
            if h.filter_fn is not None and not h.filter_fn(obj):
                return
            if kind == "add" and h.add_fn is not None:
                h.add_fn(obj)
            elif kind == "update" and h.update_fn is not None:
                h.update_fn(old, obj)
            elif kind == "delete" and h.delete_fn is not None:
                h.delete_fn(obj)
        except Exception:
            logger.exception("informer handler failed (%s %s)", kind, obj)
