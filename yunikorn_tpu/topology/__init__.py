"""Fleet topology model: slice / rack / ICI-domain coordinates and the
contention- and topology-aware placement built on them (round 15).

Real TPU fleets are not flat node lists: chips within one ICI domain talk
over the inter-chip interconnect at orders of magnitude higher bandwidth
than across domains, pod slices define which nodes can form a mesh at all,
and co-tenant traffic inside a domain degrades everyone sharing it
(BandPilot, PAPERS.md: performance-plus-contention-aware dispatch beats
capacity-only scoring in AI clusters). This package turns node topology
labels into dense integer coordinates on `NodeArrays` (mirrored to the
device like every other node field) and derives the three consumers:

  score.py      the solver-side steering — a contention-penalty /
                domain-empty term in the batched score plus a per-gang
                preferred-ICI-domain plan folded through refined constraint
                groups (ops/assign.py consumes it behind `solver.topology`)
  model.py      label parsing + interning, per-domain aggregates, the
                fragmentation measure the obs gauge reports
  (pack)        ops/pack_solve.py partitions along ICI-domain boundaries in
                `partitioner="topo"` mode — the mesh-aligned partitioner
                that lets `parallel.mesh.PACK_SHARDED_SUPPORTED` hold

Everything is strictly additive: with `solver.topology=off` (or no topology
labels anywhere) no topology argument is ever built and every solver path
runs the exact program it ran before this package existed.
"""
from yunikorn_tpu.topology.model import (  # noqa: F401
    LABEL_ICI_DOMAIN,
    LABEL_RACK,
    LABEL_SLICE,
    TOPOLOGY_LABELS,
    domain_free_units,
    fragmentation,
    normalize_topology_labels,
    parse_topology_labels,
)
