"""Topology label model: canonical labels, provider aliases, interning and
per-domain aggregate math.

Coordinates are carried per node as three interned int32 ids
(slice, rack, ICI domain) in `NodeArrays.topo` with -1 = unlabeled. The ICI
domain is the load-bearing coordinate: it is the contention/contiguity unit
the solver steers on. Domain identity is scoped WITHIN a slice — two slices
may both label a domain "ici-0", and those are different interconnects — so
the interned domain key is the (slice, ici) pair.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

# canonical labels (what the encoder parses; the kube adapter normalizes
# provider-specific labels into these at decode time)
LABEL_SLICE = "topology.yunikorn.io/slice"
LABEL_RACK = "topology.yunikorn.io/rack"
LABEL_ICI_DOMAIN = "topology.yunikorn.io/ici-domain"
TOPOLOGY_LABELS = (LABEL_SLICE, LABEL_RACK, LABEL_ICI_DOMAIN)

# provider label aliases -> canonical, applied by the kube adapter
# (client/k8s_codec.decode_node) so downstream only ever sees the canonical
# set. GKE TPU slices carry the pod-slice name; the standard K8s zone label
# is NOT mapped (a cloud zone is a failure domain, not an interconnect).
PROVIDER_ALIASES: Dict[str, str] = {
    "cloud.google.com/gke-tpu-slice": LABEL_SLICE,
    "cloud.google.com/gke-tpu-topology-slice": LABEL_SLICE,
    "topology.kubernetes.io/rack": LABEL_RACK,
    "cloud.google.com/gke-tpu-ici-domain": LABEL_ICI_DOMAIN,
}


def normalize_topology_labels(labels: Dict[str, str]) -> Dict[str, str]:
    """Fold provider aliases into the canonical topology labels (canonical
    keys win when both are present). Returns the same dict object when no
    alias applies — the adapter's hot path stays allocation-free."""
    hit = None
    for alias, canon in PROVIDER_ALIASES.items():
        if alias in labels and canon not in labels:
            if hit is None:
                hit = dict(labels)
            hit[canon] = labels[alias]
    return hit if hit is not None else labels


def parse_topology_labels(
        labels: Dict[str, str]) -> Tuple[Optional[str], Optional[str],
                                         Optional[Tuple[str, str]]]:
    """(slice key, rack key, ici-domain key) from one node's labels.

    The ici key is the (slice, ici) pair — domain names are slice-scoped
    (see module docstring); unlabeled slices scope their domains under ""
    so a labels-only-ici cluster still gets distinct domains."""
    sl = labels.get(LABEL_SLICE)
    rack = labels.get(LABEL_RACK)
    ici = labels.get(LABEL_ICI_DOMAIN)
    return sl, rack, ((sl or "", ici) if ici is not None else None)


def domain_free_units(node_dom: np.ndarray, free_i: np.ndarray,
                      cap_i: np.ndarray, n_dom: int,
                      score_cols: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Per-ICI-domain (free units, capacity units) as int64 arrays [n_dom].

    "Units" are the solver's capacity-normalized objective quantized to
    integer millis (pack_solve's inv_scale, ×1024): incommensurable vocab
    columns (milliCPU vs bytes) sum on one scale, and the integer form keeps
    every downstream comparison exact/deterministic."""
    sc = score_cols if score_cols > 0 else free_i.shape[1]
    inv = 1024.0 / np.maximum(
        np.asarray(cap_i[:, :sc], np.float64).mean(axis=0), 1.0)
    valid = node_dom >= 0
    dom = np.clip(node_dom, 0, max(n_dom - 1, 0)).astype(np.int64)
    fu = np.rint(np.maximum(free_i[:, :sc], 0) * inv[None, :]).sum(axis=1)
    cu = np.rint(np.maximum(cap_i[:, :sc], 0) * inv[None, :]).sum(axis=1)
    free_d = np.zeros((max(n_dom, 1),), np.int64)
    cap_d = np.zeros((max(n_dom, 1),), np.int64)
    np.add.at(free_d, dom[valid], fu[valid].astype(np.int64))
    np.add.at(cap_d, dom[valid], cu[valid].astype(np.int64))
    return free_d[:n_dom], cap_d[:n_dom]


def fleet_fragmentation(node_arrays, free_delta=None) -> float:
    """ICI-domain fragmentation of a NodeArrays fleet's CURRENT free
    capacity — the one shared recipe (dtype floors, invalid-row convention,
    optional in-flight overlay) behind the scheduler gauge, the replay
    fingerprint and the topology bench, so the three can never diverge.
    0.0 when the fleet carries no ICI-domain labels."""
    na = node_arrays
    n_dom = na.num_ici_domains
    if n_dom <= 0:
        return 0.0
    free_i = np.floor(na.free).astype(np.int64)
    if free_delta is not None:
        from yunikorn_tpu.ops.assign import apply_free_delta

        free_i = np.maximum(apply_free_delta(free_i, free_delta), 0)
    cap_i = np.floor(na.capacity_arr).astype(np.int64)
    free_d, _cap_d = domain_free_units(na.topo[:, 2], free_i, cap_i, n_dom)
    return fragmentation(free_d)


def fragmentation(free_d: np.ndarray) -> float:
    """ICI-domain fragmentation of the fleet's free capacity in [0, 1].

    0 = every free unit sits in one domain (a whole-domain gang can land
    without crossing the ICI boundary); → 1 as the free capacity scatters
    evenly across many domains. Defined as 1 − max_d(free_d)/Σ_d(free_d);
    0 when there is no topology or no free capacity."""
    if free_d.size == 0:
        return 0.0
    total = int(free_d.sum())
    if total <= 0:
        return 0.0
    return round(1.0 - int(free_d.max()) / total, 6)
