"""Solver-side topology steering: the contention-penalty score term and the
per-gang preferred-ICI-domain plan (BandPilot-style dispatch).

Two layers, both strictly score-level (feasibility is never touched):

  node level    every ask is penalized for landing in an ICI domain already
                loaded with co-tenant traffic and rewarded for a
                domain-empty placement — the BandPilot contention term,
                evaluated inside the jitted solve from two tiny [D] arrays.

  gang level    asks are grouped per application ("the gang"); a host-side
                greedy pre-pass picks each gang a target ICI domain by
                segmented per-domain contiguity score — a domain the WHOLE
                gang fits into, preferring domains the app already occupies
                (stickiness) and co-tenant-free domains, charging each
                chosen domain's free AND busy side as it goes so
                same-cycle gangs spread instead of stampeding one domain.
                The plan reaches the kernel as a per-ask target
                (`pref_pod`): the segmented per-domain gang fill
                (ops/assign._topo_gang_proposals) proposes every steered
                pod into its domain through the existing accept machinery,
                and the argmax fallback carries the same preferred-domain
                bonus — no group refinement, so the steered solve's cost
                is independent of gang count.

The pre-pass is O(gangs × domains) host numpy — gangs per cycle are small
(hundreds), domains are small (tens) — and fully deterministic, so the
differential suites can pin its output. Everything here is bypassed when
`solver.topology` is off or the cluster carries no topology labels:
`build_topo_args` then returns None and the solve runs the exact
pre-topology program (the bit-identical-off contract).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from yunikorn_tpu.snapshot.vocab import _next_pow2 as _bucket
from yunikorn_tpu.topology.model import domain_free_units, fragmentation

# gangs are applications with >= this many asks in the batch; smaller apps
# only get gang steering when they already hold allocations (stickiness)
MIN_GANG_ASKS = 2
# int32 ceiling for the [D] unit arrays shipped to the device
_UNIT_CAP = np.int64(2**31 - 1)


@dataclasses.dataclass
class TopoArgs:
    """Everything `ops.assign.solve` (and pack_solve) needs for topology
    steering, numpy-ready. Steering is per-POD (`pref_pod`), so no group
    refinement exists and the cost of the steered solve is independent of
    how many gangs the batch carries."""
    pref_pod: np.ndarray      # [N] int32 target ICI domain per ask (-1 none)
    node_dom: np.ndarray      # [M] int32 node -> ICI domain (-1 = none)
    dom_busy: np.ndarray      # [D] int32 co-tenant busy units per domain
    dom_cap: np.ndarray       # [D] int32 capacity units per domain
    stats: dict = dataclasses.field(default_factory=dict)

    def as_tuple(self) -> tuple:
        return (self.node_dom, self.pref_pod, self.dom_busy, self.dom_cap)


def _ask_units(req: np.ndarray, cap_i: np.ndarray,
               score_cols: int = 0) -> np.ndarray:
    """Per-ask capacity-normalized demand in integer milli-units — the same
    scale domain_free_units uses, so fits compare exactly."""
    sc = score_cols if score_cols > 0 else req.shape[1]
    inv = 1024.0 / np.maximum(
        np.asarray(cap_i[:, :sc], np.float64).mean(axis=0), 1.0)
    return np.rint(np.maximum(req[:, :sc], 0)
                   * inv[None, :]).sum(axis=1).astype(np.int64)


def plan_gang_domains(
        gang_order: Sequence[str],
        gang_demand: Dict[str, int],
        gang_presence: Dict[str, np.ndarray],
        free_d: np.ndarray, cap_d: np.ndarray) -> Dict[str, int]:
    """Greedy, rank-ordered gang → ICI-domain plan (deterministic).

    For each gang (in scheduling order) pick the domain maximizing
    (whole-gang fits, own presence, co-tenant-free, least busy-fraction,
    most remaining free, lowest id), then charge the domain's remaining free
    capacity with the gang's demand so later gangs see what is left — the
    segmented per-domain contiguity score that makes ICI-contiguous slots
    the preferred landing zone."""
    D = free_d.shape[0]
    if D == 0:
        return {}
    rem = free_d.astype(np.int64).copy()
    busy = np.maximum(cap_d.astype(np.int64) - free_d, 0)
    cap = np.maximum(cap_d.astype(np.int64), 1)
    ids = np.arange(D)
    out: Dict[str, int] = {}
    for app in gang_order:
        demand = gang_demand.get(app, 0)
        pres = gang_presence.get(app)
        pres = pres if pres is not None else np.zeros((D,), np.int64)
        fits = (rem >= demand).astype(np.int64)
        empty = (busy == 0).astype(np.int64)
        # integer busy fraction (milli): deterministic, no float ties.
        # Recomputed per gang — each plan CHARGES its domain's busy side
        # too, so the next gang sees it as contended and spreads instead of
        # stampeding the one least-busy domain (the feedback the per-cycle
        # in-kernel score cannot provide across gangs of one batch).
        busy_milli = (busy * 1000) // cap
        # lexicographic max via np.lexsort (last key is primary)
        order = np.lexsort((ids, -rem, busy_milli, -empty, -pres, -fits))
        best = int(order[0])
        out[app] = best
        rem[best] = max(rem[best] - demand, 0)
        busy[best] += demand
    return out


def build_topo_args(admitted, batch, node_arrays,
                    app_rows: Dict[str, List[int]],
                    score_cols: int = 0, free_delta=None) -> Optional[TopoArgs]:
    """Assemble TopoArgs for one solve batch, or None when the fleet
    carries no ICI-domain labels (the topology-off identity path).

    admitted: the batch's asks in scheduling order; app_rows: node rows of
    each relevant application's EXISTING allocations (domain stickiness).
    free_delta: the core's in-flight allocation overlay ([capacity, R]
    float) — the gang planner and the contention term must see the same
    overlay-reduced free capacity the solve's fit checks see, or a domain
    filled by still-in-flight commits looks open and the plan steers gangs
    into spill. The caller gates scope: locality and host-port batches
    never get here (locality constraints already express placement
    structure, and the core keeps their solve inputs exactly as before)."""
    from yunikorn_tpu.ops.assign import apply_free_delta

    na = node_arrays
    node_dom = np.ascontiguousarray(na.topo[:, 2])
    n_dom = na.num_ici_domains
    if n_dom <= 0 or not (node_dom >= 0).any():
        return None
    free_i = np.floor(na.free).astype(np.int64)
    if free_delta is not None:
        free_i = np.maximum(apply_free_delta(free_i, free_delta), 0)
    cap_i = np.floor(na.capacity_arr).astype(np.int64)
    # invalid rows carry zeroed free/capacity already (remove_node clears
    # them), so the domain aggregates only count live nodes
    free_d, cap_d = domain_free_units(node_dom, free_i, cap_i, n_dom,
                                      score_cols)
    busy_d = np.maximum(cap_d - free_d, 0)

    n = batch.num_pods
    units = _ask_units(batch.req[:n], cap_i, score_cols)

    # ---- gang discovery: group asks per application, scheduling order ----
    gang_order: List[str] = []
    gang_asks: Dict[str, List[int]] = {}
    for i, ask in enumerate(admitted[:n]):
        app = ask.application_id
        if app not in gang_asks:
            gang_asks[app] = []
            gang_order.append(app)
        gang_asks[app].append(i)
    gang_presence: Dict[str, np.ndarray] = {}
    for app, rows in app_rows.items():
        if not rows:
            continue
        pres = np.zeros((n_dom,), np.int64)
        doms = node_dom[np.asarray(rows, np.int64)]
        doms = doms[(doms >= 0) & (doms < n_dom)]
        np.add.at(pres, doms, 1)
        gang_presence[app] = pres
    steered = [app for app in gang_order
               if len(gang_asks[app]) >= MIN_GANG_ASKS
               or gang_presence.get(app) is not None]
    gang_demand = {app: int(units[gang_asks[app]].sum()) for app in steered}
    plan = plan_gang_domains(steered, gang_demand, gang_presence,
                             free_d, cap_d)

    # per-pod target domains: the plan lands on every member ask (padding
    # rows and unsteered asks stay -1)
    pref_pod = np.full((batch.req.shape[0],), -1, np.int32)
    for app in steered:
        dom = plan.get(app, -1)
        if dom >= 0:
            pref_pod[np.asarray(gang_asks[app], np.int64)] = dom

    D_pad = _bucket(n_dom, 4)
    busy_arr = np.zeros((D_pad,), np.int32)
    cap_arr = np.zeros((D_pad,), np.int32)
    busy_arr[:n_dom] = np.minimum(busy_d, _UNIT_CAP).astype(np.int32)
    cap_arr[:n_dom] = np.minimum(cap_d, _UNIT_CAP).astype(np.int32)
    return TopoArgs(
        pref_pod=pref_pod,
        node_dom=node_dom.astype(np.int32),
        dom_busy=busy_arr,
        dom_cap=cap_arr,
        stats={
            "domains": int(n_dom),
            "gangs": len(steered),
            # computed here where free_d is already in hand — the caller's
            # fragmentation gauge reuses it instead of re-aggregating the
            # fleet (review finding: the double domain_free_units pass)
            "fragmentation": fragmentation(free_d),
            "plan": {app: int(plan[app]) for app in steered if app in plan},
        },
    )


def preempt_node_order(candidate_names: Sequence[str],
                       node_arrays) -> List[str]:
    """Reorder preemption candidate nodes so victim selection prefers
    freeing CONTIGUOUS ICI domains: domains holding the most free capacity
    come first (evicting there soonest opens a whole domain for a gang),
    stable cache order within a domain, unlabeled nodes last.

    The scheduler feeds this single list to BOTH planners (the device
    kernel's node_order ranking and the host loop's iteration order,
    ops/preempt_solve.py + core/preemption.py), so the exact-parity
    contract between them is preserved by construction."""
    na = node_arrays
    node_dom = na.topo[:, 2]
    n_dom = na.num_ici_domains
    if n_dom <= 0:
        return list(candidate_names)
    free_i = np.floor(na.free).astype(np.int64)
    cap_i = np.floor(na.capacity_arr).astype(np.int64)
    free_d, _ = domain_free_units(node_dom, free_i, cap_i, n_dom)
    keyed = []
    for pos, name in enumerate(candidate_names):
        idx = na.index_of(name)
        dom = int(node_dom[idx]) if idx is not None else -1
        if 0 <= dom < n_dom:
            keyed.append((-int(free_d[dom]), dom, pos, name))
        else:
            keyed.append((1, n_dom, pos, name))  # unlabeled: after all domains
    keyed.sort()
    return [name for _, _, _, name in keyed]
