"""Core-side partition state: applications, allocations, node registry.

Role-equivalent to yunikorn-core's PartitionContext (the reference links it
in-process; the shim's MockScheduler asserts against it, reference
pkg/shim/scheduler_mock_test.go:295 GetActiveNodeCountInCore). Tracks the
core's view: per-app pending asks + allocations, per-queue accounting, node
schedulable states. Placement capacity itself lives in the shim's
SchedulerCache (shared in-process) — the core overlays scheduling state, it
does not duplicate pod bookkeeping.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AllocationAsk, Allocation, TaskGroup, UserGroupInfo


# Core-side application states (subset of yunikorn-core's application state
# machine relevant to the shim protocol: New/Accepted/Running/Completing/
# Completed/Failing/Failed/Resuming/Rejected)
APP_NEW = "New"
APP_ACCEPTED = "Accepted"
APP_RUNNING = "Running"
APP_COMPLETING = "Completing"
APP_COMPLETED = "Completed"
APP_FAILING = "Failing"
APP_FAILED = "Failed"
APP_RESUMING = "Resuming"
APP_REJECTED = "Rejected"


@dataclasses.dataclass
class CoreApplication:
    application_id: str
    queue_name: str
    user: UserGroupInfo
    tags: Dict[str, str]
    state: str = APP_NEW
    submit_time: float = dataclasses.field(default_factory=time.time)
    priority: int = 0
    pending_asks: Dict[str, AllocationAsk] = dataclasses.field(default_factory=dict)
    allocations: Dict[str, Allocation] = dataclasses.field(default_factory=dict)
    task_groups: List[TaskGroup] = dataclasses.field(default_factory=list)
    gang_style: str = "Soft"
    placeholder_ask: Optional[Resource] = None
    placeholder_timeout: Optional[float] = None
    reserving_since: Optional[float] = None
    # a real (non-placeholder) allocation was committed at some point:
    # distinguishes "gang done, placeholders left over" (release them on
    # completion) from "gang still reserving" (placeholder timeout owns it)
    had_real_allocation: bool = False

    def allocated_resource(self) -> Resource:
        out = Resource()
        for a in self.allocations.values():
            out = out.add(a.resource)
        return out

    def pending_resource(self) -> Resource:
        out = Resource()
        for a in self.pending_asks.values():
            out = out.add(a.resource)
        return out

    def has_placeholder_allocations(self) -> bool:
        return any(a.placeholder for a in self.allocations.values())


@dataclasses.dataclass
class CoreNode:
    node_id: str
    schedulable: bool = False   # nodes register draining (CREATE_DRAIN)
    attributes: Dict[str, str] = dataclasses.field(default_factory=dict)
    occupied: Resource = dataclasses.field(default_factory=Resource)     # foreign pods
    capacity: Resource = dataclasses.field(default_factory=Resource)


class Partition:
    def __init__(self, name: str = "default"):
        self.name = name
        self.applications: Dict[str, CoreApplication] = {}
        self.nodes: Dict[str, CoreNode] = {}
        self.foreign_allocations: Dict[str, Allocation] = {}  # key -> allocation
        # bumped whenever node membership changes; capacity memos depend on
        # it in multi-partition mode (the cache's capacity_version alone
        # doesn't see which partition a node landed in)
        self.membership_gen = 0
        # set when a config reload drops this partition: existing work drains,
        # no new apps and no new scheduling cycles
        self.draining = False

    def active_node_count(self) -> int:
        return sum(1 for n in self.nodes.values() if n.schedulable)

    def total_node_count(self) -> int:
        return len(self.nodes)

    def get_application(self, app_id: str) -> Optional[CoreApplication]:
        return self.applications.get(app_id)

    def dao(self) -> dict:
        return {
            "name": self.name,
            "applications": {
                app_id: {
                    "state": app.state,
                    "queue": app.queue_name,
                    "user": app.user.user,
                    "pendingAsks": len(app.pending_asks),
                    "allocations": {
                        k: {"nodeId": a.node_id, "placeholder": a.placeholder}
                        for k, a in app.allocations.items()
                    },
                }
                for app_id, app in self.applications.items()
            },
            "nodes": {
                nid: {"schedulable": n.schedulable, "occupied": dict(n.occupied.resources)}
                for nid, n in self.nodes.items()
            },
            "foreignAllocations": sorted(self.foreign_allocations),
        }
