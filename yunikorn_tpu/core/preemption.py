"""Preemption planner: make room for high-priority asks by evicting victims.

Role-equivalent to yunikorn-core's preemption logic, which the reference shim
serves via the PreemptionPredicates upcall (reference pkg/cache/
scheduler_callback.go:200-209 → Context.IsPodFitNodeViaPreemption
context.go:718-746 → PredicateManager.PreemptionPredicates
predicate_manager.go:137-188). The per-(pod,node) ordered-victim-subset check
with the startIndex contract lives in ops/preempt.py; this module holds TWO
planners deciding WHICH asks preempt WHERE:

  HOST (plan_preemptions) — the reference-shaped loop, kept as the
  differential-testing oracle and the fallback for asks whose constraints
  the device cannot model (host-evaluated affinity, host ports, DRA/volume
  restrictions):
    for each unplaced ask (priority order, bounded per cycle):
      candidate nodes   = feasible nodes for the ask's constraint group
      victims per node  = the node's shared victim table
                          (ops.preempt.victim_table: managed, preemptable,
                          ordered (priority asc, newest first), truncated)
                          filtered to strictly-lower priority, unclaimed
      chosen node       = feasible node minimizing (victim count, victim
                          priority sum), validated through the exact
                          victim-subset search
      emit releases     = TerminationType.PREEMPTED_BY_SCHEDULER

  DEVICE (dispatch/finish_preemption_solve) — the same decision procedure as
  ONE jitted dispatch over all asks × all nodes × all victim slots
  (ops/preempt_solve.py), reading victim tables encoded into the persistent
  device node mirror. Both planners consume ops.preempt.victim_table and the
  clamped priority-sum helper, so their choices are identical whenever the
  device models the ask (pinned by tests/test_preempt_solve.py); every
  device plan is confirmed through preemption_victim_search before any
  release is emitted, so a stale table can only cost a fallback, never an
  invalid eviction.

The shim reacts to the releases by deleting the victim pods (reference
handleReleaseAppAllocationEvent); the freed capacity is observed through the
informer path and the preempting ask wins it on the next solve cycle via its
rank (priority sorts first).

Victim-side opt-out: pods whose PriorityClass carries the
yunikorn.apache.org/allow-preemption: "false" annotation are never selected
(reference constants.AnnotationAllowPreemption). Preemptor-side opt-out: asks
whose pod sets preemptionPolicy: Never do not trigger preemption.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.common.si import (
    AllocationAsk,
    AllocationRelease,
    PreemptionPredicatesArgs,
    TerminationType,
)
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.ops.preempt import (
    MAX_CANDIDATE_NODES,
    MAX_PREEMPTING_ASKS_PER_CYCLE,
    clamped_prio_sum,
    pod_priority,
    preemption_victim_search,
    victim_table,
)

logger = log("core.scheduler")


@dataclasses.dataclass
class PreemptionPlan:
    ask: AllocationAsk
    node_id: str
    victims: List[Pod]
    # which planner actually produced this plan ("host" | "device") — a
    # device-branch pass can still emit host plans (unsupported groups,
    # confirmation fallbacks, the residue pass), and the metrics/REST
    # surfaces attribute per plan
    planner: str = "host"

    def releases(self, victim_app_ids: Dict[str, str]) -> List[AllocationRelease]:
        return [
            AllocationRelease(
                application_id=victim_app_ids.get(v.uid, ""),
                allocation_key=v.uid,
                termination_type=TerminationType.PREEMPTED_BY_SCHEDULER,
                message=f"preempted for {self.ask.allocation_key}",
            )
            for v in self.victims
        ]


def _may_preempt(ask: AllocationAsk) -> bool:
    pod = ask.pod
    if pod is not None and pod.spec.preemption_policy == "Never":
        return False
    return True


class _NodeTables:
    """Per-planning-call cache of node snapshots + shared victim tables:
    one snapshot and one table build per node per call, shared across asks
    (the pre-round-8 planner recomputed both per (ask, node))."""

    def __init__(self, cache, app_of_pod):
        self.cache = cache
        self.managed = app_of_pod.__contains__
        self.pc_lookup = cache.get_priority_class
        self._snapshots: Dict[str, object] = {}
        self._tables: Dict[str, List[Pod]] = {}

    def snapshot(self, name: str):
        if name not in self._snapshots:
            self._snapshots[name] = self.cache.snapshot_node(name)
        return self._snapshots[name]

    def table(self, name: str) -> List[Pod]:
        t = self._tables.get(name)
        if t is None:
            info = self.snapshot(name)
            t = (victim_table(info, self.pc_lookup, self.managed)
                 if info is not None else [])
            self._tables[name] = t
        return t


def plan_preemptions(
    cache,
    unplaced_asks: List[AllocationAsk],
    app_of_pod: Dict[str, str],
    inflight_by_node: Optional[Dict[str, object]] = None,
    candidate_nodes: Optional[List[str]] = None,
    already_victim: Optional[set] = None,
    max_asks: int = MAX_PREEMPTING_ASKS_PER_CYCLE,
    credit_keys: Optional[frozenset] = None,
) -> Tuple[List[PreemptionPlan], List[str]]:
    """Compute preemption plans for unplaced asks (HOST planner).

    `cache` is the shared external SchedulerCache (provides pods, nodes and
    PriorityClass lookups); app_of_pod maps victim pod uid -> application id;
    inflight_by_node carries the core's committed-but-not-yet-assumed usage
    per node (same overlay the solver applies), so victims are never evicted
    for capacity this cycle's own allocations will consume. candidate_nodes
    restricts (and orders) the nodes searched — the core passes its
    schedulable node list so both planners see identical candidates.
    already_victim seeds the claimed set (the core's residue pass after the
    device planner: victims chosen there must not be claimed twice);
    max_asks caps the asks considered (the per-cycle budget remainder).

    credit_keys (round 22, ROADMAP (d)): allocation keys holding a
    cross-shard victim credit — the fleet-wide repair pass proved free
    capacity cannot hold them, so they plan with effective priority
    max(priority, 1): a credited priority-0 ask may evict strictly-lower
    (negative-priority, i.e. preemptible/spot tier) pods it could never
    touch on its own priority. Un-credited semantics are bit-identical.

    Returns (plans, attempted_ask_keys) — attempted includes failed plans so
    the caller can put them on cooldown too.
    """
    plans: List[PreemptionPlan] = []
    attempted: List[str] = []
    already_victim = set() if already_victim is None else already_victim
    credit_keys = credit_keys or frozenset()
    node_list = (candidate_nodes if candidate_nodes is not None
                 else cache.node_names())
    tables = _NodeTables(cache, app_of_pod)
    candidates = sorted(unplaced_asks, key=lambda a: -(a.priority or 0))
    for ask in candidates[:max(max_asks, 0)]:
        credited = ask.allocation_key in credit_keys
        eff_priority = (max(ask.priority or 0, 1) if credited
                        else (ask.priority or 0))
        if eff_priority <= 0 or not _may_preempt(ask) or ask.pod is None:
            continue
        attempted.append(ask.allocation_key)
        plan = _plan_for_ask(cache, ask, already_victim,
                             inflight_by_node or {}, node_list, tables,
                             ask_priority=eff_priority)
        if plan is not None:
            for v in plan.victims:
                already_victim.add(v.uid)
            plans.append(plan)
    return plans, attempted


def _plan_for_ask(cache, ask: AllocationAsk, already_victim: set,
                  inflight_by_node: Dict[str, object],
                  node_list: List[str],
                  tables: _NodeTables,
                  ask_priority: Optional[int] = None
                  ) -> Optional[PreemptionPlan]:
    pod = ask.pod
    if ask_priority is None:
        ask_priority = ask.priority or 0
    best: Optional[Tuple[int, int, str, List[Pod]]] = None  # (count, prio_sum, node, victims)

    searched = 0
    for name in node_list:
        if searched >= MAX_CANDIDATE_NODES:
            break  # hard budget on victim-subset searches per ask
        info = tables.snapshot(name)
        if info is None:
            continue
        # quick feasibility screen ignoring capacity (host predicates)
        err = pod_fits_node(pod, info.node, info.allocatable, info.pods.values())
        if err is not None and err != "insufficient resources" and err != "host port conflict":
            continue
        # victims: the node's shared table (managed, preemptable, eviction
        # order, truncated to MAX_VICTIMS_PER_NODE) filtered to strictly
        # lower priority and not already claimed this cycle. The priority
        # filter removes a sorted SUFFIX and the claim filter only removes
        # rows, so this equals the device kernel's slot masking exactly.
        victims = [
            v for v in tables.table(name)
            if pod_priority(v) < ask_priority
            and v.uid not in already_victim
        ]
        if not victims:
            continue
        searched += 1
        resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
            allocation_key=pod.uid,
            node_id=name,
            preempt_allocation_keys=[v.uid for v in victims],
            start_index=0,
        ), extra_used=inflight_by_node.get(name))
        if not resp.success:
            continue
        chosen = victims[: resp.index + 1]
        prio_sum = clamped_prio_sum(pod_priority(v) for v in chosen)
        key = (len(chosen), prio_sum)
        if best is None or key < (best[0], best[1]):
            best = (len(chosen), prio_sum, name, chosen)
    if best is None:
        return None
    _, _, node_id, chosen = best
    logger.info("preemption: ask %s evicts %d pods on node %s",
                ask.allocation_key, len(chosen), node_id)
    return PreemptionPlan(ask=ask, node_id=node_id, victims=chosen)


# --------------------------------------------------------------------------
# Device planner: one jitted victim-selection solve for all asks
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PreemptSolveHandle:
    """An in-flight batched preemption solve: dispatch is async, the arrays
    materialize at finish — the core overlaps the commit/bind host work with
    the device computation."""
    asks: List[AllocationAsk]          # candidate order (priority desc)
    device_rows: List[bool]            # per ask: modeled on device?
    node_idx: object                   # [A] device array (async)
    victim_mask: object                # [A, V] device array (async)
    cache: object
    encoder: object
    app_of_pod: Dict[str, str]
    inflight_by_node: Dict[str, object]
    node_list: List[str]
    stats: Dict[str, object]


def dispatch_preemption_solve(
    cache,
    encoder,
    unplaced_asks: List[AllocationAsk],
    app_of_pod: Dict[str, str],
    inflight_by_node: Optional[Dict[str, object]] = None,
    candidate_nodes: Optional[List[str]] = None,
    mesh=None,
    mirror_epoch: Optional[int] = None,
    aot_pending: bool = False,
) -> Optional[PreemptSolveHandle]:
    """Encode + async-dispatch the batched victim-selection solve.

    Returns None when nothing is eligible (the caller should skip planning
    entirely) — asks in groups the device cannot model still ride the handle
    and are re-planned on the host at finish, sharing the claimed-victim set.
    aot_pending: only SUPERVISED callers opt in — an AOT-store miss in
    background mode then raises CompilePending for the ladder to absorb;
    unsupervised convenience callers (plan_preemptions_batched, scripts)
    keep the inline compile so the raise cannot escape them.
    """
    import numpy as np

    from yunikorn_tpu.ops import preempt_solve as ps_mod

    candidates = sorted(unplaced_asks, key=lambda a: -(a.priority or 0))
    asks = [a for a in candidates[:MAX_PREEMPTING_ASKS_PER_CYCLE]
            if (a.priority or 0) > 0 and _may_preempt(a) and a.pod is not None]
    if not asks:
        return None
    inflight_by_node = inflight_by_node or {}
    node_list = (candidate_nodes if candidate_nodes is not None
                 else cache.node_names())

    batch = encoder.build_batch(asks)
    gph = batch.g_preempt_host
    device_rows = []
    for i in range(len(asks)):
        gid = int(batch.group_id[i])
        device_rows.append(not bool(gph[gid]) if gph is not None else True)
    if not any(device_rows):
        # every ask exceeds the device model: the caller's plain host path
        # covers them all — skip the victim sync/upload and the dispatch
        return None

    # zombie checkpoint: a dispatch abandoned while wedged above must not
    # reach the victim tables after a replacement mirror went live
    encoder.ensure_mirror_epoch(mirror_epoch)
    synced = encoder.sync_victims(app_of_pod, cache.get_priority_class)
    na = encoder.nodes
    node_order = np.full((na.capacity,), ps_mod.NODE_ORDER_EXCLUDED, np.int32)
    for pos, name in enumerate(node_list):
        idx = na.index_of(name)
        if idx is not None:
            node_order[idx] = pos

    free_delta = None
    if inflight_by_node:
        free_delta = np.zeros((na.capacity, encoder.vocabs.resources.num_slots),
                              np.float32)
        for name, res in inflight_by_node.items():
            idx = na.index_of(name)
            if idx is not None:
                row = encoder.quantize_request(res)
                free_delta[idx, : row.shape[0]] += row

    from yunikorn_tpu.snapshot.encoder import MirrorDiscarded

    device_state = None
    try:
        device_state = encoder.victim_arrays(mesh=mesh, epoch=mirror_epoch)
    except MirrorDiscarded:
        raise  # abandoned-dispatch zombie: stop, don't fall back
    except Exception:
        logger.exception("victim-table device refresh failed; "
                         "falling back to per-call upload")

    np_args = ps_mod.prepare_preempt_args(
        batch, len(asks), [(a.priority or 0) for a in asks], na, node_order,
        free_delta=free_delta, device_state=device_state)
    # rows the device cannot model leave the solve (their claims would skew
    # later asks' eligibility against the host re-plan at finish)
    if not all(device_rows):
        a_valid = np_args[3].copy()
        for i, ok in enumerate(device_rows):
            if not ok:
                a_valid[i] = False
        np_args = np_args[:3] + (a_valid,) + np_args[4:]

    jc0 = ps_mod.preempt_jit_cache_entries()
    if mesh is not None:
        from yunikorn_tpu.parallel.mesh import preempt_solve_sharded

        node_idx, victim_mask = preempt_solve_sharded(
            np_args, mesh, max_candidates=MAX_CANDIDATE_NODES,
            aot_pending=aot_pending)
    else:
        from yunikorn_tpu.aot import runtime as aot_rt

        node_idx, victim_mask = aot_rt.aot_call(
            "preempt.solve", ps_mod.preempt_solve, tuple(np_args),
            {"max_candidates": MAX_CANDIDATE_NODES},
            pending_ok=aot_pending)
    jc1 = ps_mod.preempt_jit_cache_entries()
    stats = {
        "asks": len(asks),
        "device_asks": sum(device_rows),
        "victim_nodes_synced": synced,
        "sharded": mesh is not None,
    }
    if jc0 >= 0 and jc1 >= 0:
        stats["compiled"] = jc1 > jc0
    return PreemptSolveHandle(
        asks=asks, device_rows=device_rows, node_idx=node_idx,
        victim_mask=victim_mask, cache=cache, encoder=encoder,
        app_of_pod=app_of_pod, inflight_by_node=inflight_by_node,
        node_list=node_list, stats=stats)


def finish_preemption_solve(
    handle: PreemptSolveHandle,
    only_keys: Optional[set] = None,
) -> Tuple[List[PreemptionPlan], List[str], Dict[str, object]]:
    """Materialize the solve, confirm every plan through the exact victim-
    subset search, and host-re-plan anything the device missed or that fails
    confirmation. only_keys restricts to asks still worth planning (the
    core passes the post-commit unplaced set: an ask placed since dispatch —
    e.g. by the locality-fallback drain — must neither claim victims nor pay
    a confirmation search). Returns (plans, attempted_ask_keys, stats)."""
    import numpy as np

    cache = handle.cache
    na = handle.encoder.nodes
    node_idx = np.asarray(handle.node_idx)
    victim_mask = np.asarray(handle.victim_mask)
    tables = _NodeTables(cache, handle.app_of_pod)
    plans: List[PreemptionPlan] = []
    attempted: List[str] = []
    already: set = set()
    fallbacks = 0
    for k, ask in enumerate(handle.asks):
        if only_keys is not None and ask.allocation_key not in only_keys:
            continue
        attempted.append(ask.allocation_key)
        plan: Optional[PreemptionPlan] = None
        confirmed = False
        if handle.device_rows[k] and int(node_idx[k]) >= 0:
            row = int(node_idx[k])
            name = na.name_of(row)
            uids = na.victim_uids.get(row, ())
            chosen = [uids[j] for j in range(min(len(uids), victim_mask.shape[1]))
                      if victim_mask[k, j]]
            if name is not None and chosen and not (set(chosen) & already):
                resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
                    allocation_key=ask.pod.uid,
                    node_id=name,
                    preempt_allocation_keys=chosen,
                    start_index=0,
                ), extra_used=handle.inflight_by_node.get(name))
                if resp.success:
                    # state drift since encode can only shrink the prefix;
                    # the confirmed subset is still minimal-in-order
                    chosen = chosen[: resp.index + 1]
                    victims = [v for v in (cache.get_pod(u) for u in chosen)
                               if v is not None]
                    if len(victims) == len(chosen):
                        plan = PreemptionPlan(ask=ask, node_id=name,
                                              victims=victims,
                                              planner="device")
                        confirmed = True
        if not confirmed:
            # Exact host re-plan against the shared claimed set, for: an
            # unsupported group, a stale-table confirmation failure, a
            # victim collision with an earlier plan — AND a device miss
            # (node_idx == -1): the device's freed-capacity arithmetic is
            # deliberately conservative (floored victim rows, truncated
            # tables), so a miss is not proof the host's exact search
            # would miss. The re-scan costs one pre-round-8 host pass per
            # ask, bounded by the caller's cooldown; device false
            # negatives therefore never silently suppress an eviction the
            # host planner would have made.
            if plan is None:
                plan = _plan_for_ask(cache, ask, already,
                                     handle.inflight_by_node,
                                     handle.node_list, tables)
                if plan is not None and handle.device_rows[k]:
                    fallbacks += 1
        if plan is not None:
            for v in plan.victims:
                already.add(v.uid)
            plans.append(plan)
    stats = dict(handle.stats)
    stats["fallbacks"] = fallbacks
    stats["plans"] = len(plans)
    return plans, attempted, stats


def plan_preemptions_batched(
    cache,
    encoder,
    unplaced_asks: List[AllocationAsk],
    app_of_pod: Dict[str, str],
    inflight_by_node: Optional[Dict[str, object]] = None,
    candidate_nodes: Optional[List[str]] = None,
    mesh=None,
) -> Tuple[List[PreemptionPlan], List[str], Dict[str, object]]:
    """Convenience wrapper: dispatch + finish in one call (tests, scripts).
    The core splits the two so the device solve overlaps commit host work.
    A declined dispatch (nothing eligible, or no ask the device can model)
    falls back to the host planner outright — same behavior as the core."""
    handle = dispatch_preemption_solve(
        cache, encoder, unplaced_asks, app_of_pod,
        inflight_by_node=inflight_by_node, candidate_nodes=candidate_nodes,
        mesh=mesh)
    if handle is None:
        plans, attempted = plan_preemptions(
            cache, unplaced_asks, app_of_pod,
            inflight_by_node=inflight_by_node,
            candidate_nodes=candidate_nodes)
        return plans, attempted, {"asks": len(attempted), "device_asks": 0,
                                  "plans": len(plans), "fallbacks": 0}
    return finish_preemption_solve(handle)
