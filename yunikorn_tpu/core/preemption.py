"""Preemption planner: make room for high-priority asks by evicting victims.

Role-equivalent to yunikorn-core's preemption logic, which the reference shim
serves via the PreemptionPredicates upcall (reference pkg/cache/
scheduler_callback.go:200-209 → Context.IsPodFitNodeViaPreemption
context.go:718-746 → PredicateManager.PreemptionPredicates
predicate_manager.go:137-188). The per-(ask,node) ordered-victim-subset check
with the startIndex contract lives in ops/preempt.py; this module is the
planner that decides WHICH asks preempt WHERE:

  for each unplaced ask (priority order, bounded per cycle):
    candidate nodes   = feasible nodes for the ask's constraint group
    victims per node  = lower-priority, preemptable pods, ordered by
                        (priority asc, newest first) — cheapest evictions first
    chosen node       = feasible node minimizing (victim count, victim
                        priority sum), validated through the exact
                        victim-subset search
    emit releases     = TerminationType.PREEMPTED_BY_SCHEDULER

The shim reacts to the releases by deleting the victim pods (reference
handleReleaseAppAllocationEvent); the freed capacity is observed through the
informer path and the preempting ask wins it on the next solve cycle via its
rank (priority sorts first).

Victim-side opt-out: pods whose PriorityClass carries the
yunikorn.apache.org/allow-preemption: "false" annotation are never selected
(reference constants.AnnotationAllowPreemption). Preemptor-side opt-out: asks
whose pod sets preemptionPolicy: Never do not trigger preemption.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import (
    AllocationAsk,
    AllocationRelease,
    PreemptionPredicatesArgs,
    TerminationType,
)
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.ops.host_predicates import pod_fits_node
from yunikorn_tpu.ops.preempt import preemption_victim_search

logger = log("core.scheduler")

MAX_PREEMPTING_ASKS_PER_CYCLE = 32
MAX_CANDIDATE_NODES = 32
MAX_VICTIMS_PER_NODE = 16


@dataclasses.dataclass
class PreemptionPlan:
    ask: AllocationAsk
    node_id: str
    victims: List[Pod]

    def releases(self, victim_app_ids: Dict[str, str]) -> List[AllocationRelease]:
        return [
            AllocationRelease(
                application_id=victim_app_ids.get(v.uid, ""),
                allocation_key=v.uid,
                termination_type=TerminationType.PREEMPTED_BY_SCHEDULER,
                message=f"preempted for {self.ask.allocation_key}",
            )
            for v in self.victims
        ]


def _pod_priority(pod: Optional[Pod]) -> int:
    if pod is None or pod.spec.priority is None:
        return 0
    return pod.spec.priority


def _is_preemptable(pod: Pod, pc_lookup) -> bool:
    if pod.spec.priority_class_name:
        pc = pc_lookup(pod.spec.priority_class_name)
        if pc is not None:
            if pc.metadata.annotations.get(constants.ANNOTATION_ALLOW_PREEMPTION) == constants.FALSE:
                return False
            if getattr(pc, "preemption_policy", "") == "Never":
                # PriorityClass-level Never only blocks the preemptOR side;
                # keep victims eligible (K8s semantics)
                pass
    return True


def _may_preempt(ask: AllocationAsk) -> bool:
    pod = ask.pod
    if pod is not None and pod.spec.preemption_policy == "Never":
        return False
    return True


def plan_preemptions(
    cache,
    unplaced_asks: List[AllocationAsk],
    app_of_pod: Dict[str, str],
    inflight_by_node: Optional[Dict[str, object]] = None,
) -> Tuple[List[PreemptionPlan], List[str]]:
    """Compute preemption plans for unplaced asks.

    `cache` is the shared external SchedulerCache (provides pods, nodes and
    PriorityClass lookups); app_of_pod maps victim pod uid -> application id;
    inflight_by_node carries the core's committed-but-not-yet-assumed usage
    per node (same overlay the solver applies), so victims are never evicted
    for capacity this cycle's own allocations will consume.

    Returns (plans, attempted_ask_keys) — attempted includes failed plans so
    the caller can put them on cooldown too.
    """
    plans: List[PreemptionPlan] = []
    attempted: List[str] = []
    already_victim: set = set()
    candidates = sorted(unplaced_asks, key=lambda a: -(a.priority or 0))
    for ask in candidates[:MAX_PREEMPTING_ASKS_PER_CYCLE]:
        if (ask.priority or 0) <= 0 or not _may_preempt(ask) or ask.pod is None:
            continue
        attempted.append(ask.allocation_key)
        plan = _plan_for_ask(cache, ask, already_victim, app_of_pod,
                             inflight_by_node or {})
        if plan is not None:
            for v in plan.victims:
                already_victim.add(v.uid)
            plans.append(plan)
    return plans, attempted


def _plan_for_ask(cache, ask: AllocationAsk, already_victim: set,
                  app_of_pod: Dict[str, str],
                  inflight_by_node: Dict[str, object]) -> Optional[PreemptionPlan]:
    pod = ask.pod
    best: Optional[Tuple[int, int, str, List[Pod]]] = None  # (count, prio_sum, node, victims)
    pc_lookup = cache.get_priority_class

    node_names = cache.node_names()
    searched = 0
    for name in node_names:
        if searched >= MAX_CANDIDATE_NODES:
            break  # hard budget on victim-subset searches per ask
        info = cache.snapshot_node(name)
        if info is None:
            continue
        # quick feasibility screen ignoring capacity (host predicates)
        err = pod_fits_node(pod, info.node, info.allocatable, info.pods.values())
        if err is not None and err != "insufficient resources" and err != "host port conflict":
            continue
        # victims: lower priority, preemptable, not already claimed
        victims = [
            v for v in info.pods.values()
            if _pod_priority(v) < (ask.priority or 0)
            and v.uid not in already_victim
            and v.uid in app_of_pod          # only yunikorn-managed allocations
            and _is_preemptable(v, pc_lookup)
        ]
        if not victims:
            continue
        # cheapest evictions first: lowest priority, then youngest
        victims.sort(key=lambda v: (_pod_priority(v), -v.metadata.creation_timestamp))
        victims = victims[:MAX_VICTIMS_PER_NODE]
        searched += 1
        resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
            allocation_key=pod.uid,
            node_id=name,
            preempt_allocation_keys=[v.uid for v in victims],
            start_index=0,
        ), extra_used=inflight_by_node.get(name))
        if not resp.success:
            continue
        chosen = victims[: resp.index + 1]
        prio_sum = sum(_pod_priority(v) for v in chosen)
        key = (len(chosen), prio_sum)
        if best is None or key < (best[0], best[1]):
            best = (len(chosen), prio_sum, name, chosen)
    if best is None:
        return None
    _, _, node_id, chosen = best
    logger.info("preemption: ask %s evicts %d pods on node %s",
                ask.allocation_key, len(chosen), node_id)
    return PreemptionPlan(ask=ask, node_id=node_id, victims=chosen)
