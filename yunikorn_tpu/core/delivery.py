"""Per-shard asynchronous delivery queues: the front end's lock-free-ish
ingest path.

Before this module the ShardedCoreScheduler front end delivered every
update_allocation/update_node/update_application/update_configuration
INLINE into the target shard's CoreScheduler — a call into a wedged shard
whose cycle holds its core lock blocked the CALLER until the failover
supervisor noticed the wedge (the round-18 "pre-detection stall",
CHANGES r18). Here every delivery becomes an enqueue-and-return:

  ShardDeliveryQueue (one per shard)
      A FIFO of (method, args) deliveries drained by a DEDICATED pump
      thread that owns every front-end call into its core. The front's
      routing lock (_mu) is held only for routing-map updates and the
      enqueue itself — never across a core call — so a wedged shard
      wedges only its own queue and every front-end call stays bounded
      even before detection.

  Fencing (quarantine) / revival (rejoin)
      fence() marks the queue dead, drops the pending backlog and returns
      it — the front re-derives every dropped delivery from its own
      authoritative routing state (parked asks re-admit, node domains
      re-home via the registration map, releases re-broadcast to the
      survivors) exactly the way the round-18 quarantine transaction
      already re-homes the shard's DELIVERED state. The old pump thread
      may stay blocked forever inside the zombie core; it is epoch-fenced
      and exits the moment it unwedges. revive(core) starts a fresh pump
      for the rebuilt core.

  Backpressure
      depth() feeds the shard_queue_depth gauge; the front sheds NEW
      unpinned asks away from a queue past its high-water mark onto the
      least-loaded active shard (the shed-to-repair path in
      ShardedCoreScheduler.update_allocation) instead of deepening a
      possibly-wedged queue. Non-ask traffic (releases, node and app
      lifecycle, config) is never shed — it is small, bounded by the
      fleet's object count, and must not be reordered across shards.

Lock order: the queue's internal lock is a leaf — enqueue/fence/flush
never call out while holding it. The pump calls into the core with NO
queue or front lock held; core callbacks re-entering the front (repair
interception, rejection forget) take the front _mu only after the core
released its own lock (core/scheduler emits callbacks outside _lock), so
the sanctioned order stays acyclic: core-lock -> _mu -> leaf locks.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional, Tuple

from yunikorn_tpu.log.logger import log

logger = log("core.delivery")

# pump idle wake period: bounds how long a stop()/fence() waits for a pump
# that is blocked in Condition.wait (not in a core call)
_IDLE_WAIT_S = 0.5


class ShardDeliveryQueue:
    """Bounded-by-shedding delivery FIFO + pump thread for ONE shard."""

    def __init__(self, idx: int, core, *, high_water: int = 1024,
                 ack_observe: Optional[Callable[[int, float], None]] = None,
                 depth_set: Optional[Callable[[int, int], None]] = None):
        self.idx = idx
        self.high_water = int(high_water)
        self._mu = threading.Lock()
        self._ready = threading.Condition(self._mu)
        self._items: collections.deque = collections.deque()
        self._core = core
        self._epoch = 0
        self._dead = False
        self._stopped = False
        self._inflight = False
        self._enqueued = 0
        self._delivered = 0
        self._dropped = 0
        self._ack_observe = ack_observe
        self._depth_set = depth_set
        self._spawn_pump()

    # ------------------------------------------------------------- internals
    def _spawn_pump(self) -> None:
        t = threading.Thread(
            target=self._pump_loop, args=(self._epoch, self._core),
            name=f"shard-delivery-{self.idx}e{self._epoch}", daemon=True)
        t.start()

    def _pump_loop(self, epoch: int, core) -> None:
        while True:
            with self._mu:
                while (not self._items and not self._stopped
                       and self._epoch == epoch):
                    self._ready.wait(_IDLE_WAIT_S)
                if self._epoch != epoch or self._stopped:
                    return
                method, args, t_enq = self._items.popleft()
                self._inflight = True
            try:
                # the ONLY place front-end traffic enters this core; may
                # block indefinitely on a wedged core — that blocks this
                # pump (and this queue) alone, never a front-end caller
                getattr(core, method)(*args)
            except Exception:
                logger.exception("shard %d delivery %s failed", self.idx,
                                 method)
            dt = time.time() - t_enq
            with self._mu:
                self._inflight = False
                stale = self._epoch != epoch
                if not stale:
                    self._delivered += 1
                depth = len(self._items)
                self._ready.notify_all()
            if stale:
                # unwedged AFTER a fence: the zombie core already consumed
                # the delivery but its callback/ledger hooks are fenced
                # (quarantine re-derived the state); just exit
                return
            if self._ack_observe is not None:
                self._ack_observe(self.idx, dt)
            if self._depth_set is not None:
                self._depth_set(self.idx, depth)

    # ------------------------------------------------------------------- API
    def enqueue(self, method: str, *args) -> bool:
        """Append one delivery; returns False (dropped) when fenced."""
        with self._mu:
            if self._dead or self._stopped:
                return False
            self._items.append((method, args, time.time()))
            self._enqueued += 1
            depth = len(self._items)
            self._ready.notify_all()
        if self._depth_set is not None:
            self._depth_set(self.idx, depth)
        return True

    def depth(self) -> int:
        with self._mu:
            return len(self._items) + (1 if self._inflight else 0)

    def over_high_water(self) -> bool:
        return self.depth() >= self.high_water

    @property
    def dead(self) -> bool:
        return self._dead

    def fence(self) -> List[Tuple[str, tuple]]:
        """Quarantine: mark dead, drop + return the undelivered backlog
        (the caller re-derives it from front routing state), epoch-fence
        the pump so it exits instead of delivering into the zombie."""
        with self._mu:
            self._dead = True
            self._epoch += 1
            dropped = [(m, a) for m, a, _t in self._items]
            self._items.clear()
            self._dropped += len(dropped)
            self._ready.notify_all()
        if self._depth_set is not None:
            self._depth_set(self.idx, 0)
        return dropped

    def revive(self, core) -> None:
        """Rejoin: point at the rebuilt core and start a fresh pump (the
        fenced pump may be wedged in the zombie forever; it exits on its
        stale epoch if it ever unwedges)."""
        with self._mu:
            self._dead = False
            self._epoch += 1
            self._core = core
            self._items.clear()
            self._inflight = False
        self._spawn_pump()

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until the backlog fully drains (or timeout / fenced).
        Returns True when drained."""
        deadline = time.time() + max(0.0, timeout)
        with self._mu:
            while self._items or self._inflight:
                if self._dead or self._stopped:
                    return False
                left = deadline - time.time()
                if left <= 0:
                    return False
                self._ready.wait(min(left, _IDLE_WAIT_S))
            return True

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            self._epoch += 1
            self._items.clear()
            self._ready.notify_all()

    def stats(self) -> dict:
        with self._mu:
            return {
                "depth": len(self._items) + (1 if self._inflight else 0),
                "enqueued": self._enqueued,
                "delivered": self._delivered,
                "dropped": self._dropped,
                "dead": self._dead,
                "high_water": self.high_water,
            }
