"""The core scheduler engine: SchedulerAPI implementation driving the TPU solver.

Role-equivalent to the in-process yunikorn-core the reference starts via
entrypoint.StartAllServicesWithLogger (reference pkg/cmd/shim/main.go:54) plus
its RMProxy: the shim talks SchedulerAPI to it, it talks ResourceManagerCallback
back (reference pkg/cache/scheduler_callback.go consumes those calls).

The decisive architectural difference from the reference: the core's sequential
scheduling cycle — pick app → pick ask → probe nodes one by one via the
Predicates upcall (reference hot loop, scheduler_callback.go:196-198) — is
replaced by a batched cycle:

    collect pending asks → quota-gate per queue (exact host-side integer
    accounting) → DRF/priority/FIFO rank → encode batch → ONE jitted solve on
    TPU (predicates + scoring + conflict-free assignment for all pods × all
    nodes) → emit AllocationResponse

Gang semantics (placeholder replacement, timeout → Resuming/Failing) and
recovery (existing allocations) are handled host-side around the solve, exactly
at the same protocol seams the reference uses.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

import yaml

from yunikorn_tpu.locking import locking
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import (
    AcceptedApplication,
    AcceptedNode,
    Allocation,
    AllocationRelease,
    AllocationRequest,
    AllocationResponse,
    ApplicationRequest,
    ApplicationResponse,
    ContainerSchedulingState,
    NodeAction,
    NodeRequest,
    NodeResponse,
    RegisterResourceManagerRequest,
    RejectedAllocationAsk,
    RejectedApplication,
    RejectedNode,
    ResourceManagerCallback,
    SchedulerAPI,
    TerminationType,
    UpdateContainerSchedulingStateRequest,
    UpdatedApplication,
)
from yunikorn_tpu.core.partition import (
    APP_ACCEPTED,
    APP_COMPLETED,
    APP_COMPLETING,
    APP_FAILING,
    APP_NEW,
    APP_REJECTED,
    APP_RESUMING,
    APP_RUNNING,
    CoreApplication,
    CoreNode,
    Partition,
)
from yunikorn_tpu.core import gate as gate_mod
from yunikorn_tpu.core.gate import GateFallback, legacy_admit, vector_admit
from yunikorn_tpu.core.queues import QueueTree, parse_queues_yaml
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.obs.metrics import (
    COUNT_BUCKETS,
    LATENCY_BUCKETS_S,
    MS_BUCKETS,
    MetricsRegistry,
)
from yunikorn_tpu.obs.flightrec import FlightRecorder, FlightRecorderOptions
from yunikorn_tpu.obs.journey import JourneyLedger
from yunikorn_tpu.obs.slo import SloEngine, SloOptions
from yunikorn_tpu.obs.trace import CycleTracer
from yunikorn_tpu.ops import assign as assign_mod
from yunikorn_tpu.ops.assign import solve_batch
from yunikorn_tpu.robustness.health import HealthMonitor, solver_source
from yunikorn_tpu.robustness.supervisor import (
    ASSIGN_LADDER,
    AbandonedDispatch,
    DeadlineExceeded,
    SupervisedExecutor,
    SupervisorOptions,
)
from yunikorn_tpu.snapshot.encoder import MirrorDiscarded, SnapshotEncoder

logger = log("core.scheduler")

DEFAULT_PLACEHOLDER_TIMEOUT = 15 * 60.0  # core default when the app sets none
COMPLETING_TIMEOUT = 30.0  # Running app with nothing left → Completed after this

# Guest (repair-target) app registrations from the sharded front end carry
# this tag (core/shard.GUEST_APP_TAG): a guest shard sees only the stranded
# asks migrated into it, so it must never auto-complete the application —
# only the home shard (and the front end's fleet view) can decide that.
SHARD_GUEST_APP_TAG = "yunikorn.io/shard-guest"

# Diagnostic marker stamped on re-homed app registrations (shard failover:
# the app's home shard was quarantined and a surviving shard takes over).
# No behavior keys off it — a re-homed registration works because it does
# NOT carry the guest tag (so the new home owns completion, exactly like a
# fresh registration) and because the app already holds its fleet-wide app
# slot on the ledger (reserve/commit are idempotent per key, so the
# re-registration charges nothing). The tag exists so an operator reading
# an app's tags can tell a failover survivor from an original submission.
SHARD_REHOME_APP_TAG = "yunikorn.io/shard-rehomed"

# key namespace for app-COUNT slots on the shared GlobalQuotaLedger
# (allocation-resource charges key on the allocation key; app slots key on
# this prefix + application id, released on app removal)
SHARD_APP_SLOT_PREFIX = "app|"

# Whether solver.usePallas=auto turns the fused kernel on for TPU backends.
# Flipped by the hardware A/B (docs/PERF.md): stays False until the kernel
# measurably beats the XLA path on a real chip.
PALLAS_TPU_DEFAULT = False

import dataclasses


@dataclasses.dataclass
class SolverOptions:
    """Device-path knobs for the batched solve (conf solver.* keys).

    use_pallas / shard are tri-state: None = "auto", resolved once against
    the live backend at the first scheduling cycle — pallas only on a real
    TPU backend (the kernel targets Mosaic; on CPU the interpret path would
    be strictly slower than XLA), shard only when >1 device is visible.
    Defaults match ops.assign.solve_batch so the prewarm buckets and the
    production cycle compile the same static variants.
    """
    max_rounds: int = 16
    chunk: int = 512
    use_pallas: Optional[bool] = None
    shard: Optional[bool] = None
    # intra-cycle drain rounds for locality-fallback groups (0 = one pod per
    # group per cycle)
    fallback_rounds: int = 16
    # pod-bucket cap (ops.assign.MAX_SOLVE_PODS): larger batches run as one
    # compiled chained chunk program (assign.solve_chunked). Defaults to the
    # north-star bucket so production runs the monolithic program — the
    # fastest warm path (r4: chunking at 8192 cost 5.4× warm for zero CPU
    # compile saving)
    max_batch: int = 65536
    # two-stage pipelined cycle (solver.pipeline): overlap the host
    # encode/commit/publish with the async device solve. Tri-state: None =
    # "auto" = on; the pipeline engages only in single-partition mode and
    # falls back to the sequential cycle otherwise.
    pipeline: Optional[bool] = None
    # batched device preemption planner (solver.preemptDevice): victim
    # selection for all unplaced asks in one jitted dispatch, overlapped
    # with the commit; the host planner stays as the confirmation oracle
    # and the fallback for constraints the device can't model. Tri-state:
    # None = "auto" = on.
    preempt_device: Optional[bool] = None
    # array-form admission gate (solver.gateVectorized): quota + user/group
    # -limit admission as grouped prefix-scan arithmetic over one lexsorted
    # rank (core/gate.py), with the legacy per-ask loop as the fallback for
    # cycles the exact int64 arithmetic cannot represent. Tri-state: None =
    # "auto" = on.
    gate_vector: Optional[bool] = None
    # device-resident gate+encode pipeline (solver.gateDevice): the
    # bounded-pass jitted admission scan (ops/gate_solve.py) as the gate's
    # primary tier — supervised ladder device → host-vectorized → legacy —
    # plus the DeviceRowStore req tensor (O(changed asks) upload + device
    # gather) feeding the solve. Tri-state: None = "auto" = on.
    gate_device: Optional[bool] = None
    # differential oracle (solver.gateVerify): run the legacy loop after
    # every vectorized gate and pin the results identical — a mismatch
    # counts gate_mismatch_total and the legacy result wins. Doubles the
    # gate's host cost; test/debug knob.
    gate_verify: bool = False
    # assignment policy (solver.policy): "optimal" dispatches the jitted
    # LP/ADMM pack solver (ops/pack_solve.py) alongside the greedy solve as
    # a supervised "pack" path and commits whichever plan packs better —
    # the greedy plan is the floor (differential oracle in the gateVerify /
    # preempt-parity mold: a pack plan that does not beat greedy, fails, or
    # proves infeasible falls back for the cycle). "learned" dispatches the
    # two-tower learned scorer variant (policy/) as its own supervised
    # "policy" path behind the same oracle; "all" dispatches both (the
    # three-way duel greedy vs optimal vs learned). "greedy" = the
    # rank-ordered argmin only.
    policy: str = "greedy"
    # pack-arm flavor (solver.pack): which global-packing challenger an
    # "optimal" cycle fields — "pop" = the partitioned LP/ADMM solve
    # (ops/pack_solve.py), "cvx" = the full-fleet convex relaxation
    # (ops/cvx_solve.py, round 19); "auto" resolves to "pop" so defaults
    # stay bit-identical to round 12. Under policy="all" BOTH flavors
    # enter the duel regardless of this knob.
    pack: str = "auto"
    # learned-policy checkpoint prefix (solver.policyCheckpoint): the
    # versioned .npz+manifest pair policy/net.save_checkpoint writes. A
    # checkpoint that fails validation REJECTS at load and the previous
    # policy (or none) is retained — the learned arm then skips with
    # reason "no-checkpoint" instead of scoring garbage.
    policy_checkpoint: str = ""
    # topology-aware placement (solver.topology): ICI-domain contention
    # penalty + per-gang preferred-domain steering in the batched score,
    # topology-ordered preemption candidates, and the mesh-aligned pack
    # partitioner (topology/ package). Tri-state: None = "auto" = on when
    # the fleet carries topology labels, a no-op otherwise; "false" keeps
    # every solver path bit-identical to the pre-topology programs.
    topology: Optional[bool] = None

    @classmethod
    def from_conf(cls, conf) -> "SolverOptions":
        tri = {"auto": None, "true": True, "false": False}
        # chunk must divide the (power-of-two padded) batch size: round an
        # operator-set value down to a power of two instead of letting
        # solve()'s divisibility assert kill every scheduling cycle
        chunk = max(int(conf.solver_pod_chunk), 1)
        chunk = 1 << (chunk.bit_length() - 1)
        max_batch = max(int(conf.solver_max_batch), 64)
        max_batch = 1 << (max_batch.bit_length() - 1)
        return cls(
            max_rounds=max(int(conf.solver_max_rounds), 1),
            chunk=chunk,
            use_pallas=tri.get(conf.solver_use_pallas, None),
            shard=tri.get(conf.solver_shard, None),
            fallback_rounds=max(int(conf.solver_fallback_rounds), 0),
            max_batch=max_batch,
            pipeline=tri.get(getattr(conf, "solver_pipeline", "auto"), None),
            preempt_device=tri.get(
                getattr(conf, "solver_preempt_device", "auto"), None),
            gate_vector=tri.get(getattr(conf, "solver_gate", "auto"), None),
            gate_device=tri.get(
                getattr(conf, "solver_gate_device", "auto"), None),
            gate_verify=str(getattr(conf, "solver_gate_verify",
                                    "false")).lower() == "true",
            # auto = greedy until the hardware A/B flips the default
            policy=(lambda v: v if v in ("optimal", "learned", "all")
                    else "greedy")(
                str(getattr(conf, "solver_policy", "auto")).lower()),
            pack=(lambda v: v if v in ("pop", "cvx") else "auto")(
                str(getattr(conf, "solver_pack", "auto")).lower()),
            policy_checkpoint=str(
                getattr(conf, "solver_policy_checkpoint", "") or ""),
            topology=tri.get(
                getattr(conf, "solver_topology", "auto"), None),
        )


@dataclasses.dataclass
class _PipelineCycle:
    """One in-flight pipelined cycle: the prepared batch, its async solve
    handle, and the stage timestamps the finish stage turns into metrics."""
    cycle_id: int
    admitted: List
    ranks: List[int]
    batch: object
    extra_fp: tuple            # in-flight placements baked into the encode
    encode_cached: bool
    overlapped: bool           # encode ran while a solve was in flight
    # gate/encode stats captured at prepare time (the finish stage that
    # publishes the cycle entry runs AFTER the next cycle's prepare, whose
    # gate/encode would otherwise have overwritten the live counters)
    gate_stats: dict = dataclasses.field(default_factory=dict)
    encode_rows: int = 0
    encode_reencoded: int = 0
    # device row-store upload accounting captured at prepare (rows/bytes)
    encode_device: dict = dataclasses.field(default_factory=dict)
    t_prepare_start: float = 0.0
    t_gate: float = 0.0
    t_encode_end: float = 0.0
    t_dispatched: float = 0.0
    policy: str = "binpacking"
    result: Optional["_SolveHandle"] = None
    # row→name mapping snapshotted at dispatch (commit-time remap guard)
    node_names: Optional[Dict[int, str]] = None


@dataclasses.dataclass
class _SolveHandle:
    """One supervised assignment solve: the dispatch inputs (kept so a
    degraded tier can re-solve against the exact same state), the tier the
    dispatch used, and the async result awaiting materialization."""
    admitted: List
    batch: object
    policy: str
    overlay: object
    node_mask: object
    inflight_ports: object
    tier: str = "device"
    result: Optional[object] = None   # async SolveResult (device/cpu tiers)
    allow_mesh: bool = True           # False: locality-fallback drain solves
    # encoder mirror epoch captured on the scheduler thread right before
    # each supervised execute: an abandoned dispatch that unwedges after a
    # discard finds it stale and bails instead of racing the live mirror
    mirror_epoch: Optional[int] = None
    # solver.policy=optimal: the async pack-solver plan dispatched next to
    # the greedy solve (None = pack skipped/failed; greedy is the floor)
    pack: Optional[object] = None
    pack_t0: float = 0.0              # pack dispatch start (plan-latency ms)
    # solver.policy=learned: the async learned-scorer solve dispatched as
    # its own supervised "policy" path (None = skipped/failed — the
    # effective ladder is learned-device → greedy-device → cpu → host,
    # because a missing learned plan simply leaves greedy authoritative)
    learned: Optional[object] = None
    learned_t0: float = 0.0           # learned dispatch start (inference ms)
    # solver.pack=cvx / solver.policy=all: the async full-fleet convex plan
    # dispatched as its own supervised "cvx" path (None = skipped/failed —
    # a missing cvx plan leaves the rest of the duel intact)
    cvx: Optional[object] = None
    cvx_t0: float = 0.0               # cvx dispatch start (solve-latency ms)
    # the persistent device mirror the greedy device dispatch used (single-
    # device only): the pack dispatch reuses it read-only so an optimal
    # cycle ships O(changed) node state + the row-store req gather, not a
    # full re-upload (None when greedy ran on cpu/host or mesh-sharded)
    device_state: Optional[dict] = None
    # mesh-sharded counterpart + whether the greedy solve actually ran on
    # the mesh this cycle (the sharded pack dispatch follows the greedy
    # solve's layout so the two plans see identical committed state)
    mesh_state: Optional[dict] = None
    used_mesh: bool = False


class CoreScheduler(SchedulerAPI):
    """One partition, one solver. Thread-safe via a single core lock."""

    def __init__(self, cache: SchedulerCache, interval: float = 0.1,
                 solver_policy: Optional[str] = None,
                 solver_options: Optional[SolverOptions] = None,
                 trace_spans: int = 4096,
                 supervisor_options: Optional[SupervisorOptions] = None,
                 slo_options: Optional[SloOptions] = None,
                 registry=None, shard_label: Optional[str] = None,
                 quota_ledger=None, aot_namespace: Optional[str] = None,
                 journey=None, journey_capacity: int = 8192,
                 flightrec=None, flightrec_options=None):
        self._lock = locking.RMutex()
        self.cache = cache
        # ---- control-plane sharding hooks (core/shard.py) ----
        # All four default off and the defaults are bit-identical to the
        # pre-shard scheduler: no ledger probes, per-core registry, no
        # shard label on cycle_stage_ms, no AOT fingerprint namespace.
        # quota_ledger: shared GlobalQuotaLedger — the ONLY cross-shard
        # admission coupling (reserve at gate, confirm at commit, release
        # on release/eviction/app removal). shard_label: stamps per-shard
        # series in a SHARED registry. aot_namespace: isolates this
        # shard's AOT executables under its own fingerprint namespace.
        self.quota_ledger = quota_ledger
        self.shard_label = shard_label
        self.shard_index = 0
        # device-resident usage mirror (ops/ledger_mirror): set by the
        # sharded front; None means every reserve goes straight to the
        # ledger (single-shard — no coupling to take off the hot path)
        self.usage_mirror = None
        self.aot_namespace = aot_namespace
        self._stage_kw = ({"shard": shard_label}
                          if shard_label is not None else {})
        self.encoder = SnapshotEncoder(cache)
        self.solver = solver_options or SolverOptions()
        self._solver_resolved = False
        self._use_pallas = False
        self._mesh = None
        # Multi-partition: self.partition / self.queues are the ACTIVE
        # pointers (set per request/cycle under the core lock); the dicts hold
        # every partition the config or node attributes named. The single
        # "default" partition is the common case and pays no overhead.
        self.partition = Partition()
        self.queues = QueueTree()
        self.partitions: Dict[str, Partition] = {"default": self.partition}
        self.queue_trees: Dict[str, QueueTree] = {"default": self.queues}
        self.placements: Dict[str, object] = {}      # name -> PlacementEngine
        self._partition_policy: Dict[str, str] = {}
        self._app_partition: Dict[str, str] = {}
        self._config_partitions: set = {"default"}
        self.callback: Optional[ResourceManagerCallback] = None
        self.rm_id = ""
        self._policy = solver_policy or "binpacking"
        self._policy_forced = solver_policy is not None
        self._preemption_enabled = True
        self._interval = interval
        self._ask_seq = 0
        # Allocations committed by the core but not yet visible in the shim
        # cache (AssumePod pending). The reference core tracks node allocations
        # itself; here the cache is shared, so this overlay closes the window
        # where a freshly committed allocation would be double-counted as free.
        self._inflight: Dict[str, Allocation] = {}
        # recovery: existing allocations can arrive before their app is
        # submitted (the shim replays pods during InitializeState, app
        # submission happens on the first pump tick) — park them here
        self._pending_restores: Dict[str, List[Allocation]] = {}
        # per-partition ((capacity_version, membership_gen, multi), total) memo
        self._cap_cache: Dict[str, Tuple[Tuple[int, int, bool], Resource]] = {}
        # asks we already preempted for → timestamp; prevents stacking fresh
        # victims every cycle while the previous evictions drain
        self._preempted_for: Dict[str, float] = {}
        # ask-arrival counter observed at the last cycle start: lets the run
        # loop skip the accumulation wait when nothing new arrived
        self._seq_at_cycle = 0
        self._completing_since: Dict[str, float] = {}
        self._completing_timeout = COMPLETING_TIMEOUT
        self._running = threading.Event()
        self._wake = threading.Condition()
        self._dirty = False
        self._thread: Optional[threading.Thread] = None
        # ---- pipelined cycle state (see _pipeline_tick) ----
        # serializes pipeline ticks against direct schedule_once() callers
        self._pipeline_mu = threading.Lock()
        self._pipeline_inflight: Optional[_PipelineCycle] = None
        # asks admitted into the in-flight batch: excluded from the next
        # gate (their commit is pending) and counted against quota as
        # in-cycle admissions (conservative — exactly what the sequential
        # order would have charged)
        self._inflight_ask_keys: set = set()
        self._inflight_gate_seed: List[tuple] = []  # (queue, res, user, groups)
        self._cycle_seq = 0
        # ---- observability (obs/): declared metrics + structured tracer ----
        # Replaces the pre-round-7 flat metrics dict and the 256-tuple
        # _pipeline_trace deque. The registry is per-core (tests build many
        # cores per process; shared counters would cross-talk); the shim and
        # dispatcher attach to it through `self.obs`.
        self.obs = registry if registry is not None else MetricsRegistry()
        self.tracer = CycleTracer(capacity=max(int(trace_spans), 64))
        m = self.obs
        # ---- robustness (robustness/): supervised device dispatches ----
        # Every device path (assign solve, preempt solve, mesh dispatch,
        # device-mirror upload) runs through the supervisor: deadlines,
        # classified bounded retry, per-path circuit breakers degrading
        # device → cpu → host, half-open probes reclaiming a recovered
        # backend. The health monitor aggregates circuit state, cycle
        # failures, informer staleness (wired by the shim) and dispatcher
        # backlog into /ws/v1/health.
        self.supervisor = SupervisedExecutor(
            supervisor_options, tracer=self.tracer)
        if shard_label is not None:
            # per-shard breakers stay per-supervisor; the prefix keeps this
            # shard's path/outcome SERIES separate in the shared registry.
            # Set BEFORE attach_metrics: the watchdog gauge publishes its
            # zero series at attach time, and a prefix applied later would
            # leave a frozen unprefixed ghost pair in the shared registry.
            self.supervisor.path_label_prefix = f"s{shard_label}/"
        self.supervisor.attach_metrics(m)
        if aot_namespace:
            # enter the shard's AOT fingerprint namespace on the watchdog
            # thread that actually runs each supervised dispatch (the
            # namespace is thread-local, like aot.bypass)
            from yunikorn_tpu.aot import runtime as _aot_rt

            self.supervisor.dispatch_cm = (
                lambda: _aot_rt.namespace(aot_namespace))
        # a deadline-abandoned dispatch leaves a daemon thread that may still
        # mutate the device mirror whenever it unwedges — orphan the mirror
        # so those late writes can't tear the next cycle's refresh
        self.supervisor.on_abandon = self._on_dispatch_abandoned
        self.health = HealthMonitor()
        self.health.register("scheduling", self._scheduling_health)
        self.health.register("solver", solver_source(self.supervisor))
        self._m_cycle_failures = m.counter(
            "scheduling_cycle_failures_total",
            "scheduling cycles that raised, by pipeline stage "
            "(pre-round-9 these were swallowed into the log)",
            labelnames=("stage",))
        self._last_cycle_failure: Optional[dict] = None
        self._failure_streak = 0
        self._last_cycle_success_at = time.time()
        # stage marker the run loop reads when a tick raises (single
        # scheduler thread writes it at each stage boundary)
        self._cycle_stage: Optional[str] = None
        # set by _pipeline_finish when it abandons an in-flight cycle: the
        # run loop must not record that tick as a cycle success
        self._cycle_abandoned = False
        # reference perf test samples
        # yunikorn_scheduler_container_allocation_attempt_total; these keep
        # the established names so dashboards/tests carry over
        self._m_allocated = m.counter(
            "allocation_attempt_allocated",
            "pods allocated (batched solve + gang replacement + pinned asks)")
        self._m_failed = m.counter(
            "allocation_attempt_failed",
            "asks that finished a cycle unplaced")
        self._m_solve_cycles = m.counter("solve_count",
                                         "scheduling cycles completed")
        self._m_solve_ms = m.counter("solve_time_ms_total",
                                     "cumulative cycle wall time in ms")
        self._m_preempted = m.counter(
            "preempted_total", "allocations released by preemption planning")
        # ---- batched preemption planner (round 8) ----
        self._m_preempt_plans = m.counter(
            "preemption_plans_total",
            "preemption plans emitted, by planner (device = batched jitted "
            "victim-selection solve, host = reference-shaped loop)",
            labelnames=("planner",))
        self._m_preempt_victims = m.counter(
            "preemption_victims_total",
            "victims released by preemption, by trigger reason",
            labelnames=("reason",))
        self._m_preempt_fallback = m.counter(
            "preemption_device_fallback_total",
            "device plans re-planned on the host (stale victim table, "
            "confirmation failure, or victim collision)")
        self._m_mis_evictions = m.counter(
            "preemption_mis_evictions_total",
            "victims evicted for an ask that still had not placed when its "
            "preemption cooldown expired — wasted evictions the confirm "
            "path could not prevent (zero-tolerance SLO objective)")
        # allocation_key -> victims actually released for it; entries are
        # dropped when the ask places (eviction paid off) and counted as
        # mis-evictions when the cooldown expires with the ask still unplaced
        self._evicted_for: Dict[str, int] = {}
        self._g_preempt_last_ms = m.gauge(
            "preemption_last_plan_ms",
            "planning latency of the most recent preemption pass (ms)")
        self._m_fb_groups = m.counter(
            "locality_fallback_groups_total",
            "locality groups that overflowed the tensor encoding")
        self._m_fb_deferred = m.counter(
            "locality_fallback_deferred_total",
            "pods drained through the exact host-path fallback")
        self._m_pipeline_cycles = m.counter(
            "pipeline_cycles_total", "pipelined (two-stage) cycles finished")
        self._m_unschedulable = m.counter(
            "unschedulable_total",
            "unplaced-ask attempts by reason (one count per cycle the ask "
            "stays unplaced)", labelnames=("reason",))
        # ---- array-form admission gate (rounds 10/11) ----
        self._m_gate_path = m.counter(
            "gate_path_total",
            "admission-gate executions by path (device = bounded-pass "
            "jitted scan, vector = host array-form prefix-scan admission, "
            "legacy = per-ask loop, fallback = extraction raised "
            "GateFallback and the legacy loop ran)",
            labelnames=("path",))
        self._m_gate_mismatch = m.counter(
            "gate_mismatch_total",
            "verify-mode cycles where the vectorized gate diverged from the "
            "legacy loop (the legacy result wins; any nonzero count is a bug)")
        self._m_gate_stage = m.histogram(
            "gate_stage_ms",
            "admission-gate sub-stage latency (rank = lexsort ranking, "
            "admit = prefix-scan / per-ask-loop admission, encode = "
            "device row-store sync + req gather)",
            labelnames=("stage",), buckets=MS_BUCKETS)
        self._m_gate_passes = m.counter(
            "gate_passes_total",
            "admission-scan passes executed across cycles (device scan or "
            "host vectorized; the device pass count is bounded by "
            "ceil(log2(n))+C by construction)")
        # ---- optimal packing (round 12, solver.policy=optimal) ----
        self._m_pack = m.counter(
            "pack_plans_total",
            "pack-solver (LP/ADMM, solver.policy=optimal) cycles by outcome "
            "(won = pack plan committed, fell_back = greedy packed at least "
            "as well, skipped = batch outside the pack model or circuit "
            "open, failed = dispatch/materialize error, infeasible = plan "
            "refused by the capacity re-check — any nonzero count is a bug)",
            labelnames=("outcome",))
        self._g_pack_util = m.gauge(
            "pack_last_util",
            "most recent cycle's packed-units ratio pack/greedy "
            "(> 1 = the pack plan packed more of the cluster)")
        self._g_pack_ms = m.gauge(
            "pack_last_plan_ms",
            "dispatch-to-decision latency of the most recent pack plan (ms)")
        # ---- learned dispatch policy (round 17, solver.policy=learned) ----
        self._m_policy = m.counter(
            "policy_plans_total",
            "learned-policy (two-tower scorer, solver.policy=learned) "
            "cycles by outcome (won = learned plan committed, fell_back = "
            "the incumbent packed at least as well, skipped = no valid "
            "checkpoint / batch outside the model / circuit open, failed = "
            "dispatch or materialize error)",
            labelnames=("outcome",))
        self._m_policy_duels = m.counter(
            "policy_duels_total",
            "choose_plan duel outcomes by participating policy (won = that "
            "policy's plan committed the cycle, lost = another plan beat "
            "it) — the measured no-op guarantee: a bad checkpoint shows up "
            "here as learned/lost, never as an incident",
            labelnames=("policy", "outcome"))
        self._h_policy_ms = m.histogram(
            "policy_inference_ms",
            "dispatch-to-decision latency of the learned plan (ms): the "
            "feature extraction + two-tower inference + steered solve, "
            "overlapped with the greedy solve like the pack path",
            buckets=MS_BUCKETS)
        self._g_policy_ms = m.gauge(
            "policy_last_inference_ms",
            "most recent cycle's learned-plan latency (ms)")
        self._g_policy_util = m.gauge(
            "policy_last_util",
            "most recent cycle's packed-units ratio learned/greedy "
            "(> 1 = the learned plan packed more of the cluster)")
        self._g_policy_epoch = m.gauge(
            "policy_checkpoint_epoch",
            "training epoch of the ACTIVE learned-policy checkpoint, "
            "labelled by its content hash (a swap moves the epoch to the "
            "new hash series and zeroes the old one)",
            labelnames=("hash",))
        self._m_policy_rejected = m.counter(
            "policy_checkpoint_rejected_total",
            "learned-policy checkpoints REJECTED at load (corrupt payload, "
            "format/feature-schema/shape mismatch) — the previous policy "
            "was retained each time")
        # ---- cvx full-fleet arm (round 19, solver.pack=cvx) ----
        self._m_cvx = m.counter(
            "cvx_plans_total",
            "cvx-solver (full-fleet convex relaxation, solver.pack=cvx) "
            "cycles by outcome (won = cvx plan committed, fell_back = the "
            "incumbent packed at least as well, skipped = batch outside "
            "the full-fleet model or circuit open, failed = "
            "dispatch/materialize error, infeasible = plan refused by the "
            "capacity re-check — any nonzero count is a bug)",
            labelnames=("outcome",))
        self._h_cvx_ms = m.histogram(
            "cvx_solve_latency_ms",
            "dispatch-to-decision latency of the cvx plan (ms): the "
            "fixed-trip primal-dual relaxation + rounding + repair, "
            "overlapped with the greedy solve like the other arms",
            buckets=MS_BUCKETS)
        self._g_cvx_ms = m.gauge(
            "cvx_last_solve_ms",
            "most recent cycle's cvx plan latency (ms)")
        self._g_cvx_util = m.gauge(
            "cvx_last_util",
            "most recent cycle's packed-units ratio cvx/greedy "
            "(> 1 = the cvx plan packed more of the cluster)")
        self._m_duel_wins = m.counter(
            "duel_wins_total",
            "choose_plan_n cycles by WINNING arm (one increment per duel "
            "cycle; policy_duels_total counts per-participant outcomes) — "
            "the committed-plan mix at a glance",
            labelnames=("arm",))
        # stats of the most recent cvx dispatch/duel (skip reason, util
        # ratio, solve ms, iteration budget); ride the cycle entry
        self._last_cvx_stats: dict = {}
        # ---- topology-aware placement (round 15, solver.topology) ----
        self._m_topo_cross = m.counter(
            "topology_cross_domain_gangs_total",
            "gangs (applications placing >= 2 pods in one cycle) whose "
            "placements spanned more than one ICI domain — the cost the "
            "topology-aware score exists to minimize")
        self._m_topo_gangs = m.counter(
            "topology_gangs_total",
            "gangs (applications placing >= 2 pods in one cycle) committed "
            "while topology accounting was active — the denominator for the "
            "cross-domain ratio")
        self._g_topo_frag = m.gauge(
            "topology_domain_fragmentation",
            "ICI-domain fragmentation of the fleet's free capacity in "
            "[0, 1]: 0 = all free capacity in one domain, rising toward 1 "
            "as it scatters (topology/model.fragmentation)")
        self._m_pack_partitioner = m.counter(
            "pack_partitioner_total",
            "pack-solver dispatches by partitioner mode (random = POP "
            "seeded permutation, topo = mesh-aligned ICI-domain-boundary "
            "partitioning)", labelnames=("mode",))
        # stats of the most recent topology fold (domains, gangs planned,
        # refined groups, fragmentation); ride the cycle entry
        self._last_topo_stats: dict = {}
        # resolved solver.topology tri-state for the current cycle (set per
        # cycle: "auto" follows whether the fleet carries topology labels)
        self._topology_active = False
        # stats of the most recent pack comparison (chosen policy, util
        # ratio, plan ms); ride the cycle entry and the solve tracer span
        self._last_pack_stats: dict = {}
        # stats of the most recent learned-arm dispatch/duel (skip reason,
        # util ratio, inference ms); ride the cycle entry next to the pack
        # stats
        self._last_policy_stats: dict = {}
        # ---- learned dispatch policy state (round 17) ----
        # the ACTIVE validated checkpoint (policy/net.PolicyCheckpoint) or
        # None; swapped atomically by set_policy_checkpoint — a rejected
        # load never touches it
        self._policy_ckpt = None
        # optional per-cycle duel recorder (policy/train.DatasetWriter or
        # any callable taking the raw-example dict): the trace-replay
        # --dataset-out hook that turns the scheduler into its own
        # training-data source. Failures are swallowed — recording must
        # never touch the scheduling path.
        self.policy_recorder = None
        if getattr(self.solver, "policy_checkpoint", ""):
            self.set_policy_checkpoint(self.solver.policy_checkpoint)
        # single-device mirror used by the most recent greedy device
        # dispatch (stashed by _dispatch_solve for the pack dispatch),
        # plus its mesh-sharded counterpart and whether the mesh ran
        self._last_solve_device_state = None
        self._last_solve_mesh_state = None
        self._last_solve_used_mesh = False
        # stats of the most recent gate pass (path, passes, sub-stage ms);
        # ride the cycle entry and the gate tracer span
        self._last_gate_stats: dict = {}
        # device row-store upload accounting of the most recent encode
        # (rows/bytes actually shipped — the O(changed) transfer contract)
        self._last_encode_device: dict = {}
        # per-cycle queue-meta cache: (key, {qname: (leaf, share, adj)}) —
        # leaf resolution, DRF dominant share and priority adjustment are
        # pure functions of the tree's accounting epoch + cluster capacity
        self._gate_meta_cache: Optional[tuple] = None
        # ask-level extraction cache (gate.AskExtractCache): the flatten's
        # per-ask Python derivation runs only for changed asks — the
        # O(changed) analog of the encoder's row cache
        self._gate_extract_cache = gate_mod.AskExtractCache()
        # in-flight quantized-row cache for _inflight_overlay: allocation
        # key -> quantized request row (quantize once per allocation, not
        # once per allocation per cycle)
        self._inflight_row_cache: Dict[str, object] = {}
        self._m_transfer_bytes = m.counter(
            "device_transfer_bytes_total",
            "host->device bytes: persistent node-mirror uploads + sharded "
            "replicated pod args")
        self._m_compiles = m.counter(
            "solve_compile_total",
            "solve dispatches that traced+compiled a new program variant")
        self._m_compile_hits = m.counter(
            "solve_compile_cache_hit_total",
            "solve dispatches served entirely from the jit cache")
        self._m_pod_e2e = m.histogram(
            "pod_e2e_latency_seconds",
            "per-pod end-to-end latency: ask submitted to core -> pod bound",
            buckets=LATENCY_BUCKETS_S)
        self._m_pod_stage = m.histogram(
            "pod_stage_latency_seconds",
            "per-pod span stages: schedule = submit->commit, "
            "bind = commit->bound", labelnames=("stage",),
            buckets=LATENCY_BUCKETS_S)
        self._m_cycle_stage = m.histogram(
            "cycle_stage_ms",
            "per-cycle stage latency distribution"
            + (" (per shard)" if shard_label is not None else ""),
            labelnames=(("stage", "shard") if shard_label is not None
                        else ("stage",)), buckets=MS_BUCKETS)
        self._m_batch_pods = m.histogram(
            "solve_batch_pods", "pods per dispatched solve batch",
            buckets=COUNT_BUCKETS)
        self._g_pipeline = {
            k: m.gauge("pipeline_" + k,
                       "last pipelined cycle: " + k.replace("_", " "))
            for k in ("overlap_ratio", "overlap_ms", "encode_ms",
                      "solve_ms", "commit_ms")}
        # per-partition last-cycle stage breakdown (DAO / JSON surface;
        # the cycle_* gauges mirror it for Prometheus)
        self._last_cycle: Dict[str, dict] = {}
        # per-pod latency spans: allocation_key -> [t_submit, t_commit,
        # cycle_id]; own mutex so bind worker threads never touch the core
        # lock (observe_pod_bound)
        self._pod_spans: Dict[str, list] = {}
        self._span_mu = threading.Lock()
        # filled by _dispatch_solve for the cycle's trace span
        self._last_solve_stats: dict = {}
        # recent preemption plans (operator surface: /ws/v1/preemptions)
        from collections import deque

        self._recent_preemptions = deque(maxlen=128)
        # last-K cycle entries (flight-recorder bundle payload; the
        # last_cycle dict only keeps one entry per partition)
        self._cycle_log = deque(maxlen=64)
        # ---- SLO engine (round 14, obs/slo.py) ----
        # per-partition completion stamps feeding the cycle-staleness
        # objective; written by _note_cycle_success (run-loop ticks only —
        # staleness is a property of the LOOP, so direct schedule_once
        # callers never arm it)
        self._cycle_done_at: Dict[str, float] = {}
        self._slo_started_at: Optional[float] = None
        # wall of the first cycle with admitted pods (the AOT cold-start
        # objective's measured value); stamped once per process lifetime
        self._first_cycle_ms: Optional[float] = None
        self.slo = SloEngine(slo_options, registry=m)
        self.slo.attach_core(self)
        # ---- journey ledger + flight recorder (round 20) ----
        # journey: per-pod hop timeline admitted → gated → solved →
        # committed → bound, stamped with the SAME wall clocks as the
        # pod-span e2e histogram so the stage sum tiles the measured
        # latency exactly. A sharded front passes ONE shared ledger to
        # every shard (it owns the metrics); solo cores build their own.
        self.journey = (journey if journey is not None
                        else JourneyLedger(capacity=journey_capacity,
                                           registry=m))
        # flight recorder: post-mortem bundles on SLO violation / breaker
        # exhaustion / watchdog abandonment (+ quarantine and manual
        # triggers wired by the owner). A sharded front likewise shares
        # one recorder fleet-wide and registers the fleet-level sources;
        # a solo core records its own rings.
        if flightrec is None:
            flightrec = FlightRecorder(
                flightrec_options or FlightRecorderOptions(), registry=m)
            self._register_flightrec_sources(flightrec)
        self.flightrec = flightrec
        # both hooks fire OUTSIDE their engines' locks (see slo.py /
        # supervisor.py) — the recorder's sources re-enter them
        self.slo.on_violation = self._on_slo_violation
        self.supervisor.on_exhausted = self._on_breaker_exhausted
        # per-cycle delta baselines for the journey's solved-mark attrs
        self._aot_hits_seen = 0.0
        self._ledger_retries_seen = 0

    # ------------------------------------------------------------ SchedulerAPI
    def register_resource_manager(self, request: RegisterResourceManagerRequest,
                                  callback: ResourceManagerCallback) -> None:
        with self._lock:
            self.rm_id = request.rm_id
            self.callback = callback
            self._load_config(request.config)
        logger.info("resource manager %s registered (policy=%s)", request.rm_id, self._policy)

    def update_configuration(self, config: str, extra_config: Dict[str, str]) -> None:
        with self._lock:
            self._load_config(config)
        self.trigger()

    def _use_partition(self, name: str) -> None:
        """Point self.partition / self.queues at `name`, creating the
        partition lazily (nodes may carry a partition attribute the config
        never declared; yunikorn-core auto-registers)."""
        name = name or "default"
        part = self.partitions.get(name)
        if part is None:
            part = self.partitions[name] = Partition(name)
            self.queue_trees[name] = QueueTree()
        self.partition = part
        self.queues = self.queue_trees[name]

    def _load_config(self, config_text: str) -> None:
        from yunikorn_tpu.core.placement import PlacementEngine, parse_placement_rules

        doc = {}
        if config_text:
            try:
                doc = yaml.safe_load(config_text) or {}
            except yaml.YAMLError:
                logger.warning("invalid queues.yaml ignored")
                doc = {}
        part_names = [p.get("name", "default") for p in doc.get("partitions", [])] or ["default"]
        for pname in part_names:
            cfg = parse_queues_yaml(config_text or "", partition=pname)
            if pname not in self.partitions:
                self.partitions[pname] = Partition(pname)
                self.queue_trees[pname] = QueueTree()
            self.partitions[pname].draining = False  # re-added after removal
            self.queue_trees[pname].reload(cfg)
        # partitions the PREVIOUS config declared but the new one dropped:
        # delete when empty, otherwise drain (no new apps, no scheduling) —
        # lazily node-created partitions are untouched
        for stale in self._config_partitions - set(part_names) - {"default"}:
            part = self.partitions.get(stale)
            if part is None:
                continue
            if not part.nodes and not part.applications:
                self.partitions.pop(stale, None)
                self.queue_trees.pop(stale, None)
            else:
                part.draining = True
                logger.warning("partition %s removed from config; draining", stale)
            self.placements.pop(stale, None)
            self._partition_policy.pop(stale, None)
        self._config_partitions = set(part_names)
        for part in doc.get("partitions", []):
            pname = part.get("name", "default")
            rules = parse_placement_rules(part)
            if rules:
                self.placements[pname] = PlacementEngine(rules)
            else:
                self.placements.pop(pname, None)
            nsp = (part.get("nodesortpolicy") or {}).get("type", "")
            if nsp == "binpacking":
                self._partition_policy[pname] = "binpacking"
            elif nsp in ("fair", "fairness"):
                self._partition_policy[pname] = "spread"
            if pname == "default" and not self._policy_forced:
                self._policy = self._partition_policy.get(pname, self._policy)
                pre = part.get("preemption") or {}
                if "enabled" in pre:
                    self._preemption_enabled = bool(pre["enabled"])
        self._use_partition("default")

    def validate_configuration(self, config_text: str) -> Tuple[bool, str]:
        """/ws/v1/validate-conf analog (used by the admission controller)."""
        try:
            cfg = parse_queues_yaml(config_text or "")
            if config_text.strip() and cfg is None:
                return False, "no root queue found for partition"
            return True, ""
        except yaml.YAMLError as e:
            return False, f"invalid yaml: {e}"

    def update_node(self, request: NodeRequest) -> None:
        resp = NodeResponse()
        with self._lock:
            for info in request.nodes:
                nid = info.node_id
                if info.action in (NodeAction.CREATE, NodeAction.CREATE_DRAIN):
                    # SI node-partition attribute routes the node (reference
                    # si.AttributeKeys; one node belongs to one partition)
                    self._use_partition(
                        info.attributes.get("si/node-partition")
                        or info.attributes.get("partition") or "default")
                else:
                    self._use_partition(self._node_partition_of(nid))
                if info.action in (NodeAction.CREATE, NodeAction.CREATE_DRAIN):
                    # a node belongs to exactly ONE partition; a re-register
                    # under a different partition attribute must not register
                    # it twice (both solves would place onto it)
                    if any(nid in p.nodes for p in self.partitions.values()):
                        resp.rejected.append(RejectedNode(nid, "node already registered"))
                        continue
                    node = CoreNode(
                        node_id=nid,
                        schedulable=(info.action == NodeAction.CREATE),
                        attributes=dict(info.attributes),
                        capacity=info.schedulable_resource or Resource(),
                        occupied=info.occupied_resource or Resource(),
                    )
                    self.partition.nodes[nid] = node
                    self.partition.membership_gen += 1
                    self.encoder.set_node_schedulable(nid, node.schedulable)
                    for alloc in info.existing_allocations:
                        self._restore_allocation(alloc)
                    resp.accepted.append(AcceptedNode(nid))
                elif info.action == NodeAction.UPDATE:
                    node = self.partition.nodes.get(nid)
                    if node is None:
                        resp.rejected.append(RejectedNode(nid, "unknown node"))
                        continue
                    if info.schedulable_resource is not None:
                        node.capacity = info.schedulable_resource
                    if info.occupied_resource is not None:
                        node.occupied = info.occupied_resource
                elif info.action == NodeAction.DRAIN_TO_SCHEDULABLE:
                    node = self.partition.nodes.get(nid)
                    if node is not None:
                        node.schedulable = True
                        self.encoder.set_node_schedulable(nid, True)
                elif info.action == NodeAction.DRAIN_NODE:
                    node = self.partition.nodes.get(nid)
                    if node is not None:
                        node.schedulable = False
                        self.encoder.set_node_schedulable(nid, False)
                elif info.action == NodeAction.DECOMISSION:
                    if self.partition.nodes.pop(nid, None) is not None:
                        self.partition.membership_gen += 1
                    self.encoder.set_node_schedulable(nid, False)
        if (resp.accepted or resp.rejected) and self.callback is not None:
            self.callback.update_node(resp)
        self.trigger()

    def update_application(self, request: ApplicationRequest) -> None:
        resp = ApplicationResponse()
        with self._lock:
            for add in request.new:
                pname = add.partition or "default"
                part = self.partitions.get(pname)
                if part is None or getattr(part, "draining", False):
                    # unlike nodes, apps never create partitions: yunikorn-core
                    # rejects submissions to a partition the config (or node
                    # set) does not know
                    resp.rejected.append(RejectedApplication(
                        add.application_id, f"unknown or removed partition {pname!r}"))
                    continue
                self._use_partition(pname)
                existing = self.partition.applications.get(add.application_id)
                if existing is not None:
                    # idempotent: re-acknowledge so the shim FSM can progress
                    if (existing.tags.get(SHARD_GUEST_APP_TAG)
                            and not add.tags.get(SHARD_GUEST_APP_TAG)):
                        # guest -> real promotion: shard failover re-homed
                        # the app onto this shard, which now owns its
                        # completion lifecycle (_check_app_completion)
                        existing.tags.pop(SHARD_GUEST_APP_TAG, None)
                        existing.tags.update(add.tags)
                    resp.accepted.append(AcceptedApplication(add.application_id))
                    continue
                from yunikorn_tpu.core.placement import apply_namespace_quota, place_application

                engine = self.placements.get(self.partition.name)
                if engine is not None:
                    leaf = engine.place(add, self.queues)
                    if leaf is None:
                        resp.rejected.append(RejectedApplication(
                            add.application_id, "application rejected by placement rules"))
                        continue
                    placed_name = leaf.full_name
                else:
                    placed_name = place_application(add)
                    leaf = self.queues.resolve(placed_name)
                if leaf is None:
                    resp.rejected.append(RejectedApplication(
                        add.application_id, f"failed to place application: queue {placed_name!r} not usable"))
                    continue
                apply_namespace_quota(leaf, add)
                user_groups = list(add.user.groups)
                if self.quota_ledger is None:
                    # single-shard path: the local counts are the whole
                    # fleet — byte-identical to the pre-failover checks
                    if any(q.config.max_applications and q.subtree_app_count() >= q.config.max_applications
                           for q in leaf.ancestors_and_self()):
                        resp.rejected.append(RejectedApplication(
                            add.application_id, f"queue {leaf.full_name} is at maxApplications"))
                        continue
                if not leaf.submit_allowed(add.user.user, user_groups):
                    resp.rejected.append(RejectedApplication(
                        add.application_id,
                        f"user {add.user.user} is not allowed to submit to {leaf.full_name}"))
                    continue
                if self.quota_ledger is None:
                    if self.queues.any_limits() and not leaf.fits_user_app_limit(add.user.user, user_groups):
                        resp.rejected.append(RejectedApplication(
                            add.application_id,
                            f"user {add.user.user} exceeds maxApplications in {leaf.full_name}"))
                        continue
                elif not add.tags.get(SHARD_GUEST_APP_TAG):
                    # sharded path: the shared ledger is the app-COUNT
                    # authority (each shard's local counts see only its own
                    # registrations — N optimistic checks would overshoot
                    # maxApplications by up to Nx fleet-wide). The slot is
                    # reserved+confirmed atomically under "app|<id>" and
                    # released on app removal; re-registration (failover
                    # re-homing) hits the held-key fast path and charges
                    # nothing. Guests charge nothing either: the home shard
                    # already holds the app's slot.
                    slot_charges = gate_mod.app_slot_charges(
                        leaf, add.user.user, user_groups)
                    slot_key = SHARD_APP_SLOT_PREFIX + add.application_id
                    if not self.quota_ledger.reserve(slot_key, slot_charges):
                        resp.rejected.append(RejectedApplication(
                            add.application_id,
                            f"queue {leaf.full_name} is at maxApplications "
                            "(fleet-wide)"))
                        continue
                    self.quota_ledger.commit(slot_key, slot_charges)
                app = CoreApplication(
                    application_id=add.application_id,
                    queue_name=leaf.full_name,
                    user=add.user,
                    tags=dict(add.tags),
                    state=APP_ACCEPTED,
                    task_groups=list(add.task_groups),
                    gang_style=add.gang_scheduling_style or constants.GANG_STYLE_SOFT,
                    placeholder_ask=add.placeholder_ask,
                    placeholder_timeout=add.execution_timeout_seconds,
                )
                self.partition.applications[add.application_id] = app
                self._app_partition[add.application_id] = self.partition.name
                leaf.app_ids.add(add.application_id)
                leaf.add_user_app(add.user.user, list(add.user.groups))
                resp.accepted.append(AcceptedApplication(add.application_id))
                for alloc in self._pending_restores.pop(add.application_id, []):
                    self._restore_allocation(alloc)
            for rem in request.remove:
                self._use_partition(self._app_partition.get(rem.application_id, "default"))
                self._remove_application(rem.application_id)
        if (resp.accepted or resp.rejected or resp.updated) and self.callback is not None:
            self.callback.update_application(resp)
        self.trigger()

    def _remove_application(self, app_id: str) -> None:
        self._pending_restores.pop(app_id, None)
        self._completing_since.pop(app_id, None)
        self._app_partition.pop(app_id, None)
        app = self.partition.applications.pop(app_id, None)
        if app is None:
            return
        if (self.quota_ledger is not None
                and not app.tags.get(SHARD_GUEST_APP_TAG)):
            # free the fleet-wide app-COUNT slot (guests never held one)
            self.quota_ledger.release(SHARD_APP_SLOT_PREFIX + app_id)
        for key in list(app.pending_asks) + list(app.allocations):
            self._span_discard(key, outcome="released")
            if self.quota_ledger is not None:
                self.quota_ledger.release(key)
        leaf = self.queues.resolve(app.queue_name, create=False)
        if leaf is not None:
            leaf.app_ids.discard(app_id)
            leaf.remove_user_app(app.user.user, list(app.user.groups))
            for alloc in app.allocations.values():
                leaf.remove_allocated(alloc.resource)
                leaf.remove_user_allocated(app.user.user, alloc.resource,
                                           list(app.user.groups))

    def update_allocation(self, request: AllocationRequest) -> None:
        resp = AllocationResponse()
        accepted_keys: List[str] = []
        with self._lock:
            for ask in request.asks:
                self._use_partition(self._app_partition.get(ask.application_id, "default"))
                app = self.partition.applications.get(ask.application_id)
                if app is None or app.state in (APP_REJECTED, APP_COMPLETED):
                    resp.rejected.append(RejectedAllocationAsk(
                        ask.application_id, ask.allocation_key, "application not running"))
                    continue
                self._ask_seq += 1
                ask.seq = self._ask_seq
                app.pending_asks[ask.allocation_key] = ask
                accepted_keys.append(ask.allocation_key)
            for alloc in request.allocations:
                if alloc.foreign:
                    self._use_partition(self._node_partition_of(alloc.node_id))
                    self._track_foreign(alloc)
                else:
                    self._use_partition(self._app_partition.get(alloc.application_id, "default"))
                    self._restore_allocation(alloc)
            rel_totals: Dict[Tuple[str, str], Dict[str, int]] = {}
            rel_user_totals: Dict[Tuple[str, str], Dict[Tuple[str, tuple], Dict[str, int]]] = {}
            for release in request.releases:
                self._use_partition(self._app_partition.get(release.application_id, "default"))
                rel = self._release_allocation(
                    release, batch_acc=(rel_totals, rel_user_totals))
                if rel is not None:
                    resp.released.append(rel)
            self._apply_release_accounting(rel_totals, rel_user_totals)
            # inside the lock: the scheduler thread gates under this same
            # lock, so a pod can never be admitted (or even bound) before
            # its submit timestamp exists — a post-release _span_submit
            # could land AFTER observe_pod_bound's pop and leak the entry
            if accepted_keys:
                self._span_submit(accepted_keys)
        if (resp.new or resp.released or resp.rejected) and self.callback is not None:
            self.callback.update_allocation(resp)
        self.trigger()

    # -------------------------------------------------- allocation bookkeeping
    def _restore_allocation(self, alloc: Allocation) -> None:
        """Recovery path: an allocation that already exists in the cluster."""
        app = self.partition.applications.get(alloc.application_id)
        if app is None:
            # recovery race: park until the app submission arrives
            self._pending_restores.setdefault(alloc.application_id, []).append(alloc)
            return
        if alloc.allocation_key in app.allocations:
            return
        app.allocations[alloc.allocation_key] = alloc
        app.pending_asks.pop(alloc.allocation_key, None)
        # the pod just became yunikorn-managed (a preemption candidate)
        # with no cache-side pod event — the node's victim table is stale
        self.encoder.mark_victims_stale(alloc.node_id)
        leaf = self.queues.resolve(app.queue_name, create=False)
        if leaf is not None:
            leaf.add_allocated(alloc.resource)
            if leaf.has_limits_in_chain():
                leaf.add_user_allocated(app.user.user, alloc.resource,
                                        list(app.user.groups))
        if self.quota_ledger is not None:
            # recovery commits outside the gate: force-charge the ledger
            self.quota_ledger.commit(
                alloc.allocation_key,
                self._ledger_charges_of(app, alloc.resource))

    def _track_foreign(self, alloc: Allocation) -> None:
        # The shim re-sends a foreign allocation whenever (node, resource)
        # changes; un-count the tracked predecessor or occupied drifts up on
        # every update/move. The predecessor may live in a DIFFERENT partition
        # (the pod moved nodes across a partition boundary), so search all of
        # them like _release_allocation does.
        for part in self.partitions.values():
            prev = part.foreign_allocations.pop(alloc.allocation_key, None)
            if prev is not None:
                old_node = part.nodes.get(prev.node_id)
                if old_node is not None:
                    old_node.occupied = old_node.occupied.sub(prev.resource)
                break
        self.partition.foreign_allocations[alloc.allocation_key] = alloc
        node = self.partition.nodes.get(alloc.node_id)
        if node is not None:
            node.occupied = node.occupied.add(alloc.resource)

    def _node_partition_of(self, node_id: str) -> str:
        if node_id in self.partition.nodes:
            return self.partition.name
        for pname, part in self.partitions.items():
            if node_id in part.nodes:
                return pname
        return "default"

    def _release_allocation(self, release: AllocationRelease,
                            batch_acc=None) -> Optional[AllocationRelease]:
        """Release one allocation. With batch_acc=(totals, user_totals), the
        queue-accounting walk is deferred and accumulated — a 50k-pod mass
        release pays one ancestor walk per leaf instead of one per pod
        (_apply_release_accounting applies the sums)."""
        # journey terminal outcome: preemption victims are attributed as
        # such; the sharded repair pass's pull-release is NOT a terminal
        # (the front re-submits the same ask to another shard — its
        # journey re-admits with a repair hop, it did not end)
        if (getattr(release, "message", "") or "").startswith("shard repair"):
            _j_outcome = None
        elif release.termination_type == TerminationType.PREEMPTED_BY_SCHEDULER:
            _j_outcome = "preempted"
        else:
            _j_outcome = "released"
        self._span_discard(release.allocation_key, outcome=_j_outcome)
        if self.quota_ledger is not None:
            # drops whatever the key holds on the shared ledger: a pending
            # ask's reservation, a committed allocation's usage, or nothing
            self.quota_ledger.release(release.allocation_key)
        # foreign release (carries no app id; search the partitions)
        for part in self.partitions.values():
            foreign = part.foreign_allocations.pop(release.allocation_key, None)
            if foreign is not None:
                node = part.nodes.get(foreign.node_id)
                if node is not None:
                    node.occupied = node.occupied.sub(foreign.resource)
                return None
        app = self.partition.applications.get(release.application_id)
        if app is None:
            # the pod may have been parked for restore before its app arrived
            parked = self._pending_restores.get(release.application_id)
            if parked:
                parked[:] = [a for a in parked if a.allocation_key != release.allocation_key]
                if not parked:
                    self._pending_restores.pop(release.application_id, None)
            return None
        app.pending_asks.pop(release.allocation_key, None)
        self._inflight.pop(release.allocation_key, None)
        alloc = app.allocations.pop(release.allocation_key, None)
        if alloc is None:
            return None
        # no longer managed: the node's victim table is stale until the
        # shim's pod deletion lands in the cache
        self.encoder.mark_victims_stale(alloc.node_id)
        if batch_acc is not None:
            totals, user_totals = batch_acc
            qname = (self.partition.name, app.queue_name)
            _acc_resource(totals.setdefault(qname, {}), alloc.resource)
            if self.queues.any_limits():
                _acc_resource(
                    user_totals.setdefault(qname, {}).setdefault(
                        (app.user.user, tuple(app.user.groups)), {}),
                    alloc.resource)
        else:
            leaf = self.queues.resolve(app.queue_name, create=False)
            if leaf is not None:
                leaf.remove_allocated(alloc.resource)
                if leaf.has_limits_in_chain():
                    leaf.remove_user_allocated(app.user.user, alloc.resource,
                                               list(app.user.groups))
        return AllocationRelease(
            application_id=release.application_id,
            allocation_key=release.allocation_key,
            termination_type=release.termination_type,
            message=release.message,
        )

    def _apply_release_accounting(self, totals, user_totals) -> None:
        """Apply accumulated release sums: one ancestor walk per touched leaf."""
        for (pname, qname), acc in totals.items():
            tree = self.queue_trees.get(pname)
            leaf = tree.resolve(qname, create=False) if tree is not None else None
            if leaf is None:
                continue
            leaf.remove_allocated(Resource(acc))
            if leaf.has_limits_in_chain():
                for (user, groups), uacc in user_totals.get((pname, qname), {}).items():
                    leaf.remove_user_allocated(user, Resource(uacc), list(groups))

    # ----------------------------------------------------------- solve cycle
    def start(self) -> None:
        if self._running.is_set():
            return
        # staleness clock base: partitions that have not completed a cycle
        # yet age from loop start, not from some stale previous epoch
        self._slo_started_at = time.time()
        self._running.set()
        self._thread = threading.Thread(target=self._run_loop, name="core-scheduler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # drain any still-in-flight cycle: its allocations must commit and
        # publish before the dispatcher/shim shut down behind us
        with self._pipeline_mu:
            self._drain_pipeline()
        self.supervisor.close()

    def trigger(self) -> None:
        with self._wake:
            self._dirty = True
            self._wake.notify_all()

    def _run_loop(self) -> None:
        while self._running.is_set():
            with self._wake:
                if not self._dirty and self._pipeline_inflight is None:
                    self._wake.wait(timeout=self._interval)
                self._dirty = False
            try:
                # adaptive accumulation (SEQUENTIAL mode only): while asks
                # are still streaming in from the FSM pipeline, give them a
                # tick to land so one cycle solves one big batch instead of
                # many fragment waves (each wave pays full encode+solve
                # overhead). Bounded: at most ~10 intervals (cap 0.5s),
                # stops the moment the arrival counter goes quiet, and
                # skipped entirely on idle cycles. The PIPELINED cycle skips
                # it altogether: its overlap IS the accumulation window —
                # asks arriving during cycle N's solve+publish form cycle
                # N+1's wave, and gluing the whole burst into one giant
                # batch would serialize solve → commit → publish with
                # nothing left to overlap (measured: a single 5k-pod wave
                # binds STRICTLY later than three pipelined waves).
                if (self._ask_seq != self._seq_at_cycle
                        and not self._pipeline_enabled()
                        and self._pipeline_inflight is None):
                    deadline = time.time() + min(0.5, 10 * self._interval)
                    prev = -1
                    while self._running.is_set() and time.time() < deadline:
                        cur = self._ask_seq
                        if cur == prev:
                            break
                        prev = cur
                        time.sleep(min(self._interval / 2, 0.02))
                self._seq_at_cycle = self._ask_seq
                self._cycle_abandoned = False
                if self._pipeline_enabled():
                    self._pipeline_tick()
                else:
                    self.schedule_once()
                # a tick whose in-flight cycle was ABANDONED (solve failed on
                # every tier; _pipeline_finish swallowed it to keep the
                # pipeline moving) is a failure, not a success: skipping the
                # success note keeps the failure streak counting so the
                # health report's readiness rule can actually trip
                if not self._cycle_abandoned:
                    self._note_cycle_success()
            except Exception as e:
                # never silent (the pre-round-9 bare log line): counted by
                # stage, stamped into the health report, still logged
                if not getattr(e, "_yk_cycle_noted", False):
                    self._note_cycle_failure(self._cycle_stage or "cycle", e)
                logger.exception("scheduling cycle failed (stage=%s)",
                                 self._cycle_stage or "cycle")
            # SLO evaluation rides every loop tick, INCLUDING failed ones:
            # a failing loop is exactly when the staleness objective must
            # keep evaluating (rate-limited inside)
            self.slo.maybe_tick()

    def _pipeline_enabled(self) -> bool:
        """The two-stage pipeline engages for the single-partition case (the
        production shape); multi-partition cycles run sequentially. A cycle
        already in flight is always drained regardless (schedule_once drains
        before cycling)."""
        so = self.solver
        on = True if so.pipeline is None else so.pipeline
        return on and len(self.partitions) == 1

    def schedule_once(self) -> int:
        """One full SEQUENTIAL scheduling cycle over every partition (the
        pipelined driver lives in _pipeline_tick; a pipelined cycle still in
        flight is finished first so direct callers observe its results)."""
        total = 0
        payloads = []
        try:
            with self._pipeline_mu:
                self._cycle_stage = "sequential"
                self._drain_pipeline()
                with self._lock:
                    multi = len(self.partitions) > 1
                    for pname in list(self.partitions):
                        if getattr(self.partitions[pname], "draining", False):
                            continue  # removed from config; no new scheduling
                        self._use_partition(pname)
                        n, payload = self._schedule_partition(restrict_nodes=multi)
                        total += n
                        payloads.append(payload)
        except Exception as e:
            # count + stamp the failure here so DIRECT callers (tests, REST
            # triggers) surface in the health report too; the run loop skips
            # re-noting an already-noted exception
            if not getattr(e, "_yk_cycle_noted", False):
                self._note_cycle_failure("sequential", e)
                e._yk_cycle_noted = True
            raise
        for payload in payloads:
            self._publish_cycle(payload)
        return total

    def _resolve_solver_runtime(self) -> None:
        """Resolve the tri-state device-path gates once, at first solve.

        Deferred to here (not __init__) so constructing a CoreScheduler never
        dials the TPU relay — the backend comes up on the first cycle, which
        is also where the first compile lands anyway. Takes the core lock
        (reentrant, so calling from inside the cycle is fine): the prewarm
        thread resolves concurrently with the pump's first cycle.
        """
        with self._lock:
            self._resolve_solver_runtime_locked()

    def _resolve_solver_runtime_locked(self) -> None:
        if self._solver_resolved:
            return
        from yunikorn_tpu.utils.jaxtools import backend_or_cpu

        platform = backend_or_cpu()
        so = self.solver
        self._use_pallas = (platform == "tpu" and PALLAS_TPU_DEFAULT
                            if so.use_pallas is None else so.use_pallas)
        import jax

        n_dev = len(jax.devices())
        # auto-shard only on real accelerators: the CPU test environment
        # pins 8 virtual devices, and sharding every unit test's solve over
        # them would be pure overhead — tests opt in with shard=True
        want_shard = (n_dev > 1 and platform == "tpu") if so.shard is None else so.shard
        if want_shard and n_dev > 1:
            from yunikorn_tpu.parallel.mesh import make_mesh

            # largest power-of-two device prefix: NodeArrays capacities are
            # powers of two (min 128), so divisibility holds whenever the
            # mesh size is a power of two ≤ capacity — a non-2^k device
            # count must not wedge every cycle on the M % n_dev assertion
            mesh_n = 1 << (n_dev.bit_length() - 1)
            self._mesh = make_mesh(jax.devices()[:mesh_n])
            # sharded solves stay on the XLA path (see mesh.solve_sharded)
            self._use_pallas = False
            logger.info("solver: node-dim sharding over %d/%d %s devices",
                        mesh_n, n_dev, platform)
        else:
            self._mesh = None
        logger.info("solver runtime: platform=%s pallas=%s mesh=%s",
                    platform, self._use_pallas,
                    n_dev if self._mesh is not None else "off")
        self._solver_resolved = True

    def _partition_node_mask(self):
        """[capacity] bool mask restricting the solve to this partition's
        nodes (multi-partition only; the encoder holds the whole cache)."""
        import numpy as np

        mask = np.zeros((self.encoder.nodes.capacity,), bool)
        for nid in self.partition.nodes:
            idx = self.encoder.nodes._name_to_idx.get(nid)
            if idx is not None:
                mask[idx] = True
        return mask

    def _inflight_placements(self) -> Optional[List[Tuple[object, str]]]:
        """[(pod, node)] for committed-but-not-yet-assumed allocations —
        the locality-count analog of the free/ports overlays (extra_placed
        input of the encoder)."""
        if not self._inflight:
            return None
        out = []
        for infl in self._inflight.values():
            pod = self.cache.get_pod(infl.allocation_key)
            if pod is not None:
                out.append((pod, infl.node_id))
        return out or None

    def _policy_for_partition(self) -> str:
        return (self._policy if self._policy_forced or
                self.partition.name == "default"
                else self._partition_policy.get(self.partition.name, self._policy))

    def _on_dispatch_abandoned(self, path: str, tier: str) -> None:
        """Supervisor hook: a dispatch blew its deadline and was abandoned.

        The watchdog thread is still running the wedged call and will mutate
        whatever it was touching if it ever unwedges — for device-tier paths
        that includes the persistent device mirror's buffers and dirty-field
        bookkeeping, which the next cycle's refresh would race (a torn sync
        means wrong free-capacity tensors, i.e. wrong placements). Orphan
        the mirror so the late writes land on an unreferenced object; the
        replacement starts with one full upload."""
        # capture the evidence BEFORE touching any lock: the abandonment
        # is the incident, the rings still hold the wedged cycle
        self.flightrec.record("watchdog_abandoned",
                              reason=f"path {path} tier {tier}")
        if tier in ("cpu", "host"):
            return  # host-side tiers never touch the device mirror
        with self._lock:
            self.encoder.discard_device_mirror()

    def _on_slo_violation(self, objectives: List[str]) -> None:
        """SLO hook (fires after tick() releases its lock): one bundle per
        violation episode — the recorder's debounce folds an episode that
        flaps across objectives into a single dump."""
        self.flightrec.record("slo_violation",
                              reason="objectives: " + ",".join(objectives))

    def _on_breaker_exhausted(self, path: str) -> None:
        """Supervisor hook: every tier of a supervised path failed."""
        self.flightrec.record("breaker_exhausted", reason=f"path {path}")

    def _register_flightrec_sources(self, fr) -> None:
        """Bundle sources for a SOLO core's recorder (the sharded front
        registers fleet-level equivalents instead). Each reads leaf-locked
        state only — never the core lock, which the triggering thread (SLO
        tick, watchdog, run loop) may already hold or be wedged under."""
        fr.add_source("trace", lambda: self.tracer.chrome_trace())
        fr.add_source("metrics", lambda: self.obs.snapshot())
        fr.add_source("cycles", lambda: list(self._cycle_log))
        fr.add_source(
            "journeys", lambda: self.journey.tail(fr.options.journey_tail))
        fr.add_source("duel", lambda: {
            "last_solve": dict(self._last_solve_stats),
            "last_pack": dict(self._last_pack_stats),
            "last_policy": dict(self._last_policy_stats),
            "last_cvx": dict(self._last_cvx_stats),
        })
        fr.add_source("slo", lambda: {"verdicts": self.slo.verdicts(),
                                      "violations": self.slo.violations()})
        fr.add_source("supervisor", lambda: self.supervisor.snapshot())
        if self.quota_ledger is not None:
            fr.add_source("ledger_audit",
                          lambda: self.quota_ledger.audit())

    def _aot_outcome(self) -> str:
        """Journey solved-mark attr: did THIS cycle's dispatch load an
        executable from the AOT store ('hit'), or run entirely on already-
        warm jit caches / fresh compiles ('warm')? Delta-based on the
        store's counter so it costs one registry read per cycle."""
        c = self.obs.get("aot_store_hits_total")
        hits = float(c.value()) if c is not None else 0.0
        prev, self._aot_hits_seen = self._aot_hits_seen, hits
        return "hit" if hits > prev else "warm"

    def _journey_cycle_marks(self, keys: List[str], t_gate: float,
                             t_solve: float, gate_stats: dict,
                             solve_ms: float) -> None:
        """Stamp the sequential cycle's gated + solved journey marks (the
        pipelined cycle stamps them at its own stage boundaries)."""
        jattrs = {}
        if gate_stats.get("path") is not None:
            jattrs["gate_path"] = gate_stats["path"]
        if self.quota_ledger is not None:
            r = self.quota_ledger.contention_retries
            jattrs["ledger_retries"] = r - self._ledger_retries_seen
            self._ledger_retries_seen = r
        self.journey.mark(keys, "gated", t_gate, **jattrs)
        self.journey.mark(keys, "solved", t_solve,
                          arm=self._last_pack_stats.get("policy", "greedy"),
                          solve_ms=round(solve_ms, 2),
                          aot=self._aot_outcome())

    def _dispatch_solve(self, batch, policy, overlay, node_mask,
                        inflight_ports, allow_mesh=True, mirror_epoch=None):
        """Route one batch to the resolved solve path (sharded or single),
        threading the persistent device-resident node tensors through so the
        chunk-invariant node state transfers O(changes), not O(M), per cycle.
        The returned SolveResult is an ASYNC handle — materializing
        `.assigned` is the device sync point.

        Side channel: fills self._last_solve_stats (transfer bytes, refresh
        granularity, compile-vs-cache-hit) for the cycle's trace span, and
        feeds the matching counters — reading jit cache sizes and the
        device mirror's upload tally costs microseconds, so the clean hot
        path stays clean."""
        so = self.solver
        # an open mesh circuit drops the whole cycle to the single-device
        # shape up front: the mirror then refreshes unsharded (mesh=None) and
        # the fallback solve reuses it, instead of paying a sharded upload
        # the skipped mesh dispatch would discard plus a full per-cycle
        # transfer in the fallback. allow() half-opens a cooled-off circuit,
        # so the probe dispatch still happens here.
        use_mesh = (allow_mesh and self._mesh is not None
                    and self.encoder.nodes.capacity % self._mesh.devices.size == 0
                    and self.supervisor.allow("mesh"))
        # device-mirror upload: its own supervised path — a failing/wedged
        # upload opens the "upload" circuit and the solve falls back to the
        # per-cycle full transfer until a half-open probe re-closes it.
        # A mesh-disallowed solve (the locality-fallback drain) skips the
        # mirror whenever a mesh exists: refreshing the shared mirror with a
        # different sharding would thrash the main cycle's buffers.
        device_state = None
        if ((allow_mesh or self._mesh is None)
                and self.supervisor.allow("upload")):
            # the epoch travels from the handle (captured on the scheduler
            # thread pre-dispatch); a direct caller captures fresh here
            epoch = (mirror_epoch if mirror_epoch is not None
                     else self.encoder.mirror_epoch)
            try:
                device_state = self.supervisor.run(
                    "upload",
                    lambda: self.encoder.device_arrays(
                        mesh=self._mesh if use_mesh else None, epoch=epoch))
            except (AbandonedDispatch, MirrorDiscarded):
                raise  # zombie thread: stop, don't run a pointless solve
            except Exception:
                logger.exception("device node-state refresh failed; "
                                 "falling back to per-cycle upload")
        # mirror stashed for the cycle's pack dispatch: single-device and
        # mesh-sharded separately — the sharded pack wrapper reuses the
        # mesh mirror's committed shardings (device_put recognizes them and
        # skips the transfer), the single-device pack the unsharded one
        self._last_solve_device_state = device_state if not use_mesh else None
        self._last_solve_mesh_state = device_state if use_mesh else None
        self._last_solve_used_mesh = use_mesh
        jc0 = assign_mod.jit_cache_entries()
        # AOT background mode: a store miss on this (device) tier raises
        # CompilePending instead of stalling the cycle on an XLA compile —
        # the ladder serves from cpu/host while the compile thread populates
        # the store, and the half-open probe reclaims the tier (aot/)
        from yunikorn_tpu.aot import pending_enabled

        aot_pending = pending_enabled()
        result = None
        if use_mesh:
            from yunikorn_tpu.parallel.mesh import solve_sharded

            try:
                result = self.supervisor.run(
                    "mesh",
                    lambda: solve_sharded(
                        batch, self.encoder.nodes, self._mesh,
                        max_rounds=so.max_rounds, chunk=so.chunk,
                        policy=policy, free_delta=overlay,
                        node_mask=node_mask, ports_delta=inflight_ports,
                        max_batch=so.max_batch, device_state=device_state,
                        aot_pending=aot_pending))
            except AbandonedDispatch:
                raise  # zombie thread: stop, don't run a pointless solve
            except Exception:
                logger.exception("sharded-mesh dispatch failed; this cycle "
                                 "solves single-device")
                # the pack dispatch follows the greedy solve's layout: a
                # failed mesh must not route pack onto the mesh it just
                # watched fail (h.used_mesh contract)
                self._last_solve_used_mesh = False
                self._last_solve_mesh_state = None
        if result is None:
            result = solve_batch(batch, self.encoder.nodes, policy=policy,
                                 max_rounds=so.max_rounds, chunk=so.chunk,
                                 use_pallas=self._use_pallas,
                                 free_delta=overlay, node_mask=node_mask,
                                 ports_delta=inflight_ports,
                                 max_batch=so.max_batch,
                                 device_state=(None if use_mesh
                                               else device_state),
                                 aot_pending=aot_pending)
        jc1 = assign_mod.jit_cache_entries()
        stats = {"pods": int(batch.num_pods)}
        if jc0 >= 0 and jc1 >= 0:
            compiled = jc1 > jc0
            (self._m_compiles if compiled else self._m_compile_hits).inc()
            stats["compiled"] = compiled
        # else: jit internals don't expose cache sizes — leave both
        # counters untouched rather than mislabel every dispatch as a hit
        dev = self.encoder.device
        if dev is not None:
            b = dev.take_upload_bytes()
            if b:
                stats["node_upload_bytes"] = b
            if dev.last_refresh != "none":
                stats["node_refresh"] = dev.last_refresh
        if use_mesh:
            from yunikorn_tpu.parallel import mesh as mesh_mod

            stats["replicated_bytes"] = mesh_mod.last_replicated_bytes
        total = (stats.get("node_upload_bytes", 0)
                 + stats.get("replicated_bytes", 0))
        if total:
            self._m_transfer_bytes.inc(total)
        self._m_batch_pods.observe(batch.num_pods)
        self._last_solve_stats = stats
        return result

    # ------------------------------------------- supervised solve (tiers)
    # The assignment solve runs through the supervisor's degradation ladder:
    #   device — the resolved backend (mesh-sharded or single), async
    #   cpu    — the same program re-jitted on the host CPU backend (same
    #            arithmetic → identical placements), async
    #   host   — the exact host path (robustness/host_solve.py), pure
    #            Python/numpy, computed at materialize time
    # Dispatch and materialization are supervised separately so the
    # pipelined cycle keeps its overlap: a dispatch-time failure degrades
    # immediately; a materialize-time failure (including a blown deadline)
    # re-solves the SAME captured inputs on the next tier, so a degraded
    # cycle commits exactly what the healthy cycle would have.

    def _solve_tier_dispatch(self, h: "_SolveHandle", tier: str):
        if tier == "device":
            return self._dispatch_solve(h.batch, h.policy, h.overlay,
                                        h.node_mask, h.inflight_ports,
                                        allow_mesh=h.allow_mesh,
                                        mirror_epoch=h.mirror_epoch)
        if tier == "cpu":
            return self._dispatch_solve_cpu(h)
        return None  # host tier solves at materialize time

    def _dispatch_solve_cpu(self, h: "_SolveHandle"):
        """CPU-backend re-jitted solve: same program, same arithmetic, host
        platform — the first fallback when the device runtime is failing."""
        import jax

        from yunikorn_tpu.aot import runtime as aot_rt

        so = self.solver
        cpu = jax.local_devices(backend="cpu")[0]
        # aot bypass: the re-jitted cpu program shares the device variant's
        # avals — a store "hit" here would run the dispatch on the backend
        # this tier exists to avoid
        with jax.default_device(cpu), aot_rt.bypass():
            result = solve_batch(h.batch, self.encoder.nodes, policy=h.policy,
                                 max_rounds=so.max_rounds, chunk=so.chunk,
                                 use_pallas=False, free_delta=h.overlay,
                                 node_mask=h.node_mask,
                                 ports_delta=h.inflight_ports,
                                 max_batch=so.max_batch, device_state=None)
        self._m_batch_pods.observe(h.batch.num_pods)
        self._last_solve_stats = {"pods": int(h.batch.num_pods),
                                  "tier": "cpu"}
        return result

    def _host_assign(self, h: "_SolveHandle"):
        from yunikorn_tpu.robustness.host_solve import host_assign

        assigned = host_assign(h.admitted, h.batch, self.encoder, self.cache,
                               policy=h.policy, free_delta=h.overlay,
                               node_mask=h.node_mask,
                               ports_delta=h.inflight_ports)
        self._last_solve_stats = {"pods": int(h.batch.num_pods),
                                  "tier": "host"}
        return assigned

    def _solve_dispatch(self, admitted, batch, policy, overlay, node_mask,
                        inflight_ports, allow_mesh=True) -> "_SolveHandle":
        """Supervised dispatch on the path's current tier. Dispatch success
        alone never re-closes a half-open circuit (commit_success=False) —
        only a materialized result proves the tier healthy."""
        h = _SolveHandle(admitted=admitted, batch=batch, policy=policy,
                         overlay=overlay, node_mask=node_mask,
                         inflight_ports=inflight_ports,
                         allow_mesh=allow_mesh,
                         mirror_epoch=self.encoder.mirror_epoch)
        # solver.policy label rides every supervised dispatch + solve span
        # this cycle, so dashboards separate the greedy/optimal/learned
        # paths without new series names
        self.supervisor.policy_label = self._policy_mode()
        if allow_mesh:
            # drain solves (allow_mesh=False: the locality-fallback rounds)
            # ride the cycle's MAIN pack stats — resetting here would let a
            # drain round clobber a pack-won comparison already recorded
            self._last_pack_stats = {}
            self._last_policy_stats = {}
            self._last_cvx_stats = {}

        def mk(tier):
            return lambda: self._solve_tier_dispatch(h, tier)

        self._last_solve_device_state = None
        self._last_solve_mesh_state = None
        self._last_solve_used_mesh = False
        result, tier = self.supervisor.execute(
            "assign", [(t, mk(t)) for t in ASSIGN_LADDER],
            commit_success=False)
        h.result, h.tier = result, tier
        if tier == "device":
            h.device_state = self._last_solve_device_state
            h.mesh_state = self._last_solve_mesh_state
            h.used_mesh = self._last_solve_used_mesh
        if allow_mesh:
            self._pack_dispatch(h)
            self._cvx_dispatch(h)
            self._learned_dispatch(h)
        return h

    # --------------------------------------------- optimal packing (pack)
    # solver.policy=optimal: the jitted LP/ADMM pack solver (POP-partitioned
    # global bin packing, ops/pack_solve.py) dispatches as its own
    # single-tier supervised path NEXT TO the greedy solve — the effective
    # ladder is device-optimal → greedy-device → cpu → host-exact: a pack
    # dispatch that fails, blows its deadline, or trips its circuit leaves
    # the greedy handle authoritative, and the materialized pack plan only
    # commits when the differential comparison (choose_plan) proves it
    # strictly better packed than greedy's. Feasibility is structural: the
    # pack solver rounds/repairs through the same group-feasibility masks,
    # overlays and prefix-fit arithmetic the greedy solve uses, and the
    # free_after >= 0 re-check below refuses the plan outright otherwise.

    def _pack_on(self) -> bool:
        # "optimal" fields ONE pack flavor (solver.pack chooses; cvx
        # replaces the partitioned arm); "all" sweeps both
        p = getattr(self.solver, "policy", "greedy")
        return p == "all" or (
            p == "optimal"
            and getattr(self.solver, "pack", "auto") != "cvx")

    def _cvx_on(self) -> bool:
        p = getattr(self.solver, "policy", "greedy")
        return p == "all" or (
            p == "optimal"
            and getattr(self.solver, "pack", "auto") == "cvx")

    # ------------------------------------------- learned policy (round 17)
    # solver.policy=learned: the two-tower scorer (policy/) runs INSIDE a
    # second greedy-machinery solve — score-matrix augmentation + gated
    # proposal overrides — dispatched as its own supervised "policy" path
    # next to the greedy solve. The effective ladder is learned-device →
    # greedy-device → cpu → host: a learned dispatch that fails, blows its
    # deadline or trips its breaker leaves greedy authoritative, and the
    # materialized learned plan only commits when the N-way choose_plan
    # duel proves it strictly better. A bad checkpoint is therefore a
    # measured no-op (policy_duels_total{policy="learned",outcome="lost"}),
    # never an incident.

    def _learned_on(self) -> bool:
        return getattr(self.solver, "policy", "greedy") in ("learned", "all")

    def _policy_mode(self) -> str:
        """The configured policy label for supervised-dispatch series."""
        p = getattr(self.solver, "policy", "greedy")
        return p if p in ("optimal", "learned", "all") else "greedy"

    def set_policy_checkpoint(self, prefix: str) -> bool:
        """Load + validate a learned-policy checkpoint; REJECT on any
        mismatch and retain the previous policy. Returns True when the
        checkpoint is now active."""
        from yunikorn_tpu.policy import net as policy_net

        try:
            ck = policy_net.load_checkpoint(prefix)
        except Exception as e:
            self._m_policy_rejected.inc()
            prev = self._policy_ckpt
            logger.error(
                "policy checkpoint %s REJECTED (%s: %s); keeping previous "
                "policy (%s)", prefix, type(e).__name__, e,
                prev.hash if prev is not None else "none")
            return False
        prev, self._policy_ckpt = self._policy_ckpt, ck
        if prev is not None and prev.hash != ck.hash:
            self._g_policy_epoch.set(0.0, hash=prev.hash)
        self._g_policy_epoch.set(float(ck.epoch), hash=ck.hash)
        logger.info("policy checkpoint %s active (hash %s, epoch %d)",
                    prefix, ck.hash, ck.epoch)
        return True

    def _learned_eligible(self, h: "_SolveHandle") -> Optional[str]:
        """None when the learned arm can run this cycle; else the skip
        reason. Deterministic gates live here, before the supervised
        dispatch (the _pack_eligible rationale)."""
        if self._policy_ckpt is None:
            return "no-checkpoint"
        if h.batch.locality is not None:
            # locality rules re-rank per round on the host-visible domain
            # counts; the learned override would fight the accept caps for
            # no measured win — these cycles keep the greedy plan
            return "locality"
        if self._mesh is not None and not h.used_mesh:
            # a mesh cycle whose greedy solve did NOT run on the mesh
            # (degraded tier, failed mesh dispatch) skips the learned arm:
            # the single-device fallback would re-upload the full node
            # tensors per cycle (the round-12 rationale that gates
            # single-device pack under a mesh). Mesh cycles themselves
            # score since round 19 — the params thread through the sharded
            # wrapper (parallel.mesh.solve_sharded, policy follow-up (c)).
            return "mesh"
        return None

    def _learned_dispatch(self, h: "_SolveHandle") -> None:
        """Async-dispatch the learned-scorer solve for an eligible cycle;
        failures leave h.learned None (greedy stays authoritative)."""
        if not self._learned_on():
            return
        reason = self._learned_eligible(h)
        if reason is not None:
            self._m_policy.inc(outcome="skipped")
            self._last_policy_stats = {"skip": reason}
            return
        if not self.supervisor.allow("policy"):
            self._m_policy.inc(outcome="skipped")
            self._last_policy_stats = {"skip": "circuit"}
            return
        ck = self._policy_ckpt
        so = self.solver
        h.learned_t0 = time.perf_counter()

        use_mesh = h.used_mesh and self._mesh is not None

        def learned_fn(pending):
            # the checkpoint hash rides the AOT fingerprint extra: a
            # checkpoint swap can never serve a stale stored executable.
            # Mesh cycles route through the sharded wrapper with the params
            # replicated (follow-up (c)) — same layout as the greedy solve,
            # so the two plans see identical committed state.
            if use_mesh:
                from yunikorn_tpu.parallel import mesh as mesh_mod

                return mesh_mod.solve_sharded(
                    h.batch, self.encoder.nodes, self._mesh,
                    policy=h.policy, max_rounds=so.max_rounds,
                    chunk=so.chunk, free_delta=h.overlay,
                    node_mask=h.node_mask, ports_delta=h.inflight_ports,
                    max_batch=so.max_batch, device_state=h.mesh_state,
                    aot_pending=pending,
                    learned=(ck.params, self._cycle_seq),
                    aot_extra=("policy", ck.hash))
            return solve_batch(
                h.batch, self.encoder.nodes, policy=h.policy,
                max_rounds=so.max_rounds, chunk=so.chunk,
                free_delta=h.overlay, node_mask=h.node_mask,
                ports_delta=h.inflight_ports, max_batch=so.max_batch,
                device_state=h.device_state, aot_pending=pending,
                learned=(ck.params, self._cycle_seq),
                aot_extra=("policy", ck.hash))

        try:
            from yunikorn_tpu.aot import pending_enabled

            h.learned = self.supervisor.run(
                "policy", lambda: learned_fn(pending_enabled()),
                commit_success=False)
        except AbandonedDispatch:
            raise  # zombie thread: stop, don't continue a stale cycle
        except Exception:
            self._m_policy.inc(outcome="failed")
            self._last_policy_stats = {"skip": "error"}
            logger.exception("learned-policy dispatch failed; greedy plan "
                             "stands this cycle")

    def _pack_eligible(self, batch) -> Optional[str]:
        """None when the pack solver models this batch; else the skip
        reason (the batch takes the greedy plan for the cycle). Drain
        solves never reach here (_solve_dispatch gates on allow_mesh).
        Deterministic scope gates ALL live here, before the supervised
        dispatch: PackUnsupported raised inside supervisor.run would ride
        the transient-retry/breaker machinery (backoff sleeps on the
        scheduler thread, circuit flaps) for what is a benign skip."""
        import numpy as np

        from yunikorn_tpu.ops import pack_solve as pack_mod

        n_shards = 1
        if self._mesh is not None:
            from yunikorn_tpu.parallel import mesh as mesh_mod

            if not mesh_mod.PACK_SHARDED_SUPPORTED:
                return "mesh"
            # the sharded pack (mesh-aligned partitioner) needs whole parts
            # per shard; shape_supported verifies with the shard count
            n_shards = self._mesh.devices.size
        if batch.locality is not None:
            return "locality"
        if batch.g_ports.view(np.uint32).any():
            return "ports"
        if not pack_mod.shape_supported(batch.req.shape[0],
                                        self.encoder.nodes.capacity,
                                        n_shards=n_shards):
            # under a mesh the shard-count requirement is the binding one
            # (pick_parts doubles in powers of two, so e.g. a 6-device mesh
            # can never split into whole parts per shard) — name it
            # distinctly; single-device pack under a live mesh stays off by
            # design (it would resharded-gather every solve arg per cycle,
            # the round-12 rationale)
            if n_shards > 1 and pack_mod.shape_supported(
                    batch.req.shape[0], self.encoder.nodes.capacity):
                return "mesh-shape"
            return "shape"
        return None

    def _pack_dispatch(self, h: "_SolveHandle") -> None:
        """Async-dispatch the pack solve for an eligible optimal-policy
        cycle; failures leave h.pack None (greedy stays authoritative)."""
        if not self._pack_on():
            return
        reason = self._pack_eligible(h.batch)
        if reason is not None:
            self._m_pack.inc(outcome="skipped")
            self._last_pack_stats = {"policy": "greedy", "skip": reason}
            return
        if not self.supervisor.allow("pack"):
            self._m_pack.inc(outcome="skipped")
            self._last_pack_stats = {"policy": "greedy", "skip": "circuit"}
            return
        from yunikorn_tpu.ops import pack_solve as pack_mod

        # sharded pack follows the greedy solve onto the mesh (same layout,
        # same committed mirror); otherwise single-device, with the
        # mesh-aligned "topo" partitioner whenever topology steering is on.
        # A mesh cycle whose greedy solve did NOT run on the mesh (degraded
        # tier, failed mesh dispatch) skips pack outright: the single-device
        # fallback would re-upload the full node tensors per cycle — the
        # round-12 transfer cost the mesh gate exists to avoid
        use_mesh_pack = h.used_mesh and self._mesh is not None
        if self._mesh is not None and not use_mesh_pack:
            self._m_pack.inc(outcome="skipped")
            self._last_pack_stats = {"policy": "greedy", "skip": "mesh"}
            return
        h.pack_t0 = time.perf_counter()
        mode = ("topo" if (use_mesh_pack
                           or getattr(h.batch, "topo", None) is not None)
                else "random")
        if use_mesh_pack:
            from yunikorn_tpu.parallel import mesh as mesh_mod

            def pack_fn(pending):
                return mesh_mod.pack_solve_sharded(
                    h.batch, self.encoder.nodes, self._mesh,
                    policy=h.policy, free_delta=h.overlay,
                    node_mask=h.node_mask, ports_delta=h.inflight_ports,
                    seed=self._cycle_seq, chunk=self.solver.chunk,
                    device_state=h.mesh_state, aot_pending=pending)
        else:
            def pack_fn(pending):
                return pack_mod.pack_solve_batch(
                    h.batch, self.encoder.nodes, policy=h.policy,
                    free_delta=h.overlay, node_mask=h.node_mask,
                    ports_delta=h.inflight_ports, seed=self._cycle_seq,
                    chunk=self.solver.chunk, device_state=h.device_state,
                    aot_pending=pending, partitioner=mode)
        try:
            from yunikorn_tpu.aot import pending_enabled

            h.pack = self.supervisor.run(
                "pack", lambda: pack_fn(pending_enabled()),
                commit_success=False)
            # counted only on a dispatch that actually produced a plan, so
            # the mode ratio stays comparable to pack_plans_total outcomes
            self._m_pack_partitioner.inc(mode=mode)
        except AbandonedDispatch:
            raise  # zombie thread: stop, don't continue a stale cycle
        except pack_mod.PackUnsupported as e:
            self._m_pack.inc(outcome="skipped")
            self._last_pack_stats = {"policy": "greedy", "skip": str(e)}
        except Exception:
            self._m_pack.inc(outcome="failed")
            self._last_pack_stats = {"policy": "greedy", "skip": "error"}
            logger.exception("pack solve dispatch failed; greedy plan "
                             "stands this cycle")

    def _cvx_eligible(self, h: "_SolveHandle") -> Optional[str]:
        """None when the full-fleet convex arm models this cycle; else the
        skip reason. Deterministic scope gates live here, before the
        supervised dispatch (the _pack_eligible rationale)."""
        import numpy as np

        from yunikorn_tpu.ops import cvx_solve as cvx_mod

        batch = h.batch
        if batch.locality is not None:
            return "locality"
        if batch.g_ports.view(np.uint32).any():
            return "ports"
        if not cvx_mod.cvx_shape_supported(batch.req.shape[0],
                                           self.encoder.nodes.capacity):
            # dense [N, M] state over budget — exactly the shapes the
            # partitioned pack arm exists for
            return "shape"
        if self._mesh is not None:
            from yunikorn_tpu.parallel import mesh as mesh_mod

            if not getattr(mesh_mod, "CVX_SHARDED_SUPPORTED", False):
                return "mesh"
            if not h.used_mesh:
                # greedy degraded off the mesh this cycle: a single-device
                # cvx solve would re-upload the full node tensors (the
                # round-12 transfer-cost rationale)
                return "mesh"
        return None

    def _cvx_dispatch(self, h: "_SolveHandle") -> None:
        """Async-dispatch the full-fleet convex solve for an eligible
        cycle; failures leave h.cvx None (the rest of the duel stands)."""
        if not self._cvx_on():
            return
        reason = self._cvx_eligible(h)
        if reason is not None:
            self._m_cvx.inc(outcome="skipped")
            self._last_cvx_stats = {"skip": reason}
            return
        if not self.supervisor.allow("cvx"):
            self._m_cvx.inc(outcome="skipped")
            self._last_cvx_stats = {"skip": "circuit"}
            return
        from yunikorn_tpu.ops import cvx_solve as cvx_mod

        # the learned-dual warm start rides whenever a validated checkpoint
        # is active (DOPPLER-style water-fill fill order); its hash keys
        # the AOT fingerprint so a swap never serves a stale executable
        ck = self._policy_ckpt
        learned = ck.params if ck is not None else None
        extra = ("policy", ck.hash) if ck is not None else ()
        use_mesh_cvx = h.used_mesh and self._mesh is not None
        h.cvx_t0 = time.perf_counter()
        if use_mesh_cvx:
            from yunikorn_tpu.parallel import mesh as mesh_mod

            def cvx_fn(pending):
                return mesh_mod.cvx_solve_sharded(
                    h.batch, self.encoder.nodes, self._mesh,
                    policy=h.policy, free_delta=h.overlay,
                    node_mask=h.node_mask, ports_delta=h.inflight_ports,
                    seed=self._cycle_seq, chunk=self.solver.chunk,
                    device_state=h.mesh_state, aot_pending=pending,
                    learned=learned, aot_extra=extra)
        else:
            def cvx_fn(pending):
                return cvx_mod.cvx_solve_batch(
                    h.batch, self.encoder.nodes, policy=h.policy,
                    free_delta=h.overlay, node_mask=h.node_mask,
                    ports_delta=h.inflight_ports, seed=self._cycle_seq,
                    chunk=self.solver.chunk, device_state=h.device_state,
                    aot_pending=pending, learned=learned, aot_extra=extra)
        try:
            from yunikorn_tpu.aot import pending_enabled

            h.cvx = self.supervisor.run(
                "cvx", lambda: cvx_fn(pending_enabled()),
                commit_success=False)
        except AbandonedDispatch:
            raise  # zombie thread: stop, don't continue a stale cycle
        except cvx_mod.CvxUnsupported as e:
            self._m_cvx.inc(outcome="skipped")
            self._last_cvx_stats = {"skip": str(e)}
        except Exception:
            self._m_cvx.inc(outcome="failed")
            self._last_cvx_stats = {"skip": "error"}
            logger.exception("cvx solve dispatch failed; the cvx arm sits "
                             "out this cycle")

    def _plan_duel(self, h: "_SolveHandle", greedy_assigned):
        """Materialize every challenger plan (pack, learned) and run the
        N-way differential comparison; returns the committed assignment —
        a challenger commits only when strictly better than the incumbent
        fold (ops/pack_solve.choose_plan_n), so greedy stays the floor."""
        import numpy as np

        from yunikorn_tpu.ops import pack_solve as pack_mod

        n = h.batch.num_pods
        cands = [("greedy", np.asarray(greedy_assigned)[:n])]
        pack_ms = learned_ms = cvx_ms = None
        if h.pack is not None:
            try:
                pack_assigned, feasible = self.supervisor.run(
                    "pack",
                    lambda: (np.asarray(h.pack.assigned)[:n],
                             bool(np.asarray(h.pack.feasible))))
            except AbandonedDispatch:
                raise  # zombie thread: stop, don't commit a stale cycle
            except Exception:
                self._m_pack.inc(outcome="failed")
                self._last_pack_stats = {"policy": "greedy", "skip": "error"}
                logger.exception("pack plan materialization failed; the "
                                 "pack arm sits out this cycle")
            else:
                pack_ms = (time.perf_counter() - h.pack_t0) * 1000
                if not feasible:
                    # structurally impossible (the rounding/repair shares
                    # greedy's fit arithmetic, and pre-existing overlay
                    # negativity is excluded from the device-side check) —
                    # belt and braces: never commit such a plan
                    self._m_pack.inc(outcome="infeasible")
                    self._last_pack_stats = {"policy": "greedy",
                                             "skip": "infeasible"}
                    logger.error("pack plan over-committed capacity; the "
                                 "pack arm sits out this cycle")
                else:
                    cands.append(("optimal", pack_assigned))
        if h.cvx is not None:
            try:
                cvx_assigned, cvx_feasible = self.supervisor.run(
                    "cvx",
                    lambda: (np.asarray(h.cvx.assigned)[:n],
                             bool(np.asarray(h.cvx.feasible))))
            except AbandonedDispatch:
                raise  # zombie thread: stop, don't commit a stale cycle
            except Exception:
                self._m_cvx.inc(outcome="failed")
                self._last_cvx_stats = {"skip": "error"}
                logger.exception("cvx plan materialization failed; the "
                                 "cvx arm sits out this cycle")
            else:
                cvx_ms = (time.perf_counter() - h.cvx_t0) * 1000
                self._h_cvx_ms.observe(cvx_ms)
                if not cvx_feasible:
                    # structurally impossible (the rounding/repair shares
                    # greedy's fit arithmetic) — belt and braces: never
                    # commit such a plan
                    self._m_cvx.inc(outcome="infeasible")
                    self._last_cvx_stats = {"skip": "infeasible"}
                    logger.error("cvx plan over-committed capacity; the "
                                 "cvx arm sits out this cycle")
                else:
                    cands.append(("cvx", cvx_assigned))
        if h.learned is not None:
            try:
                learned_assigned = self.supervisor.run(
                    "policy", lambda: np.asarray(h.learned.assigned)[:n])
            except AbandonedDispatch:
                raise  # zombie thread: stop, don't commit a stale cycle
            except Exception:
                self._m_policy.inc(outcome="failed")
                self._last_policy_stats = {"skip": "error"}
                logger.exception("learned plan materialization failed; the "
                                 "learned arm sits out this cycle")
            else:
                learned_ms = (time.perf_counter() - h.learned_t0) * 1000
                self._h_policy_ms.observe(learned_ms)
                self._g_policy_ms.set(learned_ms)
                # learned placements come from the unmodified greedy accept
                # machinery (same fit masks, same prefix arithmetic), so
                # free_after >= 0 holds by construction — no extra
                # feasibility re-check is needed beyond the duel itself
                cands.append(("learned", learned_assigned))
        if len(cands) == 1:
            return greedy_assigned
        # the committed objective matches the solver's (capacity-normalized
        # units) and is priority-guarded PAIRWISE: every challenger must
        # match the incumbent class by class from the highest priority down
        # before packing quality decides, so no policy can starve a
        # high-priority ask the greedy rank order would have placed
        winner, utils = pack_mod.choose_plan_n(
            cands, h.batch.req.astype(np.int32), h.batch.valid,
            cap_i=np.floor(self.encoder.nodes.capacity_arr).astype(np.int64),
            priorities=np.asarray(
                [(a.priority or 0) for a in h.admitted], np.int64))
        by_name = dict(cands)
        g_units = max(utils["greedy"]["units_norm"], 1e-9)
        for name, _ in cands:
            self._m_policy_duels.inc(
                policy=name, outcome="won" if name == winner else "lost")
        # one increment per duel CYCLE by winning arm (the committed-plan
        # mix; policy_duels_total above is per participant)
        self._m_duel_wins.inc(arm=winner)
        if "optimal" in by_name:
            use_pack = winner == "optimal"
            util_ratio = utils["optimal"]["units_norm"] / g_units
            self._m_pack.inc(outcome="won" if use_pack else "fell_back")
            self._g_pack_util.set(util_ratio)
            self._g_pack_ms.set(pack_ms)
            self._last_pack_stats = {
                "policy": winner,
                "pack_util": round(util_ratio, 4),
                "pack_plan_ms": round(pack_ms, 2),
                "pack_placed": utils["optimal"]["placed"],
                "greedy_placed": utils["greedy"]["placed"],
                "partitioner": getattr(h.pack, "partitioner", "random"),
            }
        else:
            self._last_pack_stats = {**self._last_pack_stats,
                                     "policy": winner}
        if "cvx" in by_name:
            use_cvx = winner == "cvx"
            c_ratio = utils["cvx"]["units_norm"] / g_units
            self._m_cvx.inc(outcome="won" if use_cvx else "fell_back")
            self._g_cvx_util.set(c_ratio)
            self._g_cvx_ms.set(cvx_ms)
            self._last_cvx_stats = {
                "cvx_util": round(c_ratio, 4),
                "cvx_solve_ms": round(cvx_ms, 2),
                "cvx_iters": getattr(h.cvx, "iters", 0),
                "cvx_placed": utils["cvx"]["placed"],
                "learned_dual": bool(getattr(h.cvx, "learned_dual", False)),
            }
        if "learned" in by_name:
            use_learned = winner == "learned"
            l_ratio = utils["learned"]["units_norm"] / g_units
            self._m_policy.inc(
                outcome="won" if use_learned else "fell_back")
            self._g_policy_util.set(l_ratio)
            self._last_policy_stats = {
                "learned_util": round(l_ratio, 4),
                "learned_ms": round(learned_ms, 2),
                "learned_placed": utils["learned"]["placed"],
                "checkpoint": (self._policy_ckpt.hash
                               if self._policy_ckpt else ""),
            }
        self._record_duel(h, cands, winner)
        return by_name[winner]

    def _record_duel(self, h: "_SolveHandle", cands, winner: str) -> None:
        """Feed the optional policy_recorder one raw-tensor duel example
        (the policy/train.py training-data contract). Never throws into
        the scheduling path."""
        rec = self.policy_recorder
        if rec is None:
            return
        try:
            import numpy as np

            na = self.encoder.nodes
            free0 = np.floor(na.free).astype(np.int32)
            if h.overlay is not None:
                free0 = assign_mod.apply_free_delta(free0, h.overlay)
            node_ok = np.asarray(na.valid & na.schedulable)
            if h.node_mask is not None:
                node_ok = node_ok & np.asarray(
                    h.node_mask[: node_ok.shape[0]])
            ex = {
                "req": h.batch.req.astype(np.int32),
                "rank": np.asarray(h.batch.rank),
                "valid": np.asarray(h.batch.valid),
                "free0": free0,
                "cap": np.floor(na.capacity_arr).astype(np.int32),
                "node_ok": node_ok,
                "priorities": np.asarray(
                    [(a.priority or 0) for a in h.admitted], np.int64),
                "score_cols": int(h.batch.req.shape[1]),
                "winner": winner,
            }
            for name, assigned in cands:
                ex[f"plan_{name}"] = assigned
            rec(ex)
        except Exception:
            logger.exception("policy duel recording failed (ignored)")

    def _solve_materialize(self, h: "_SolveHandle"):
        """Finish one supervised solve: materialize the async result under
        the dispatch deadline; a failure degrades and RE-SOLVES the handle's
        captured inputs on the next tier. Raises AllTiersFailed when even
        the host tier cannot answer."""
        import numpy as np

        n = h.batch.num_pods
        # a RE-solve at materialize time is a new dispatch: it must carry
        # the current epoch, not the (possibly superseded) dispatch-time one
        h.mirror_epoch = self.encoder.mirror_epoch

        def mk(tier):
            def fn():
                if tier == h.tier and h.result is not None:
                    result, h.result = h.result, None  # retry re-dispatches
                    return np.asarray(result.assigned)[:n]
                if tier == "host":
                    return self._host_assign(h)
                result = self._solve_tier_dispatch(h, tier)
                return np.asarray(result.assigned)[:n]
            return fn

        assigned, tier = self.supervisor.execute(
            "assign", [(t, mk(t)) for t in ASSIGN_LADDER],
            start_tier=h.tier)
        h.tier = tier
        if (h.pack is not None or h.cvx is not None
                or h.learned is not None):
            # optimal/cvx/learned policy: the N-way differential comparison
            # against the greedy plan decides which assignment commits
            assigned = self._plan_duel(h, assigned)
        return assigned

    def _ask_pending(self, ask) -> bool:
        app = self.partition.applications.get(ask.application_id)
        return app is not None and ask.allocation_key in app.pending_asks

    def _commit_solve(self, admitted, batch, assigned, policy, node_mask,
                      node_names=None, cycle_id=None):
        """Commit one materialized solve (core lock held): allocation
        records, batched queue accounting, locality-fallback drain. Returns
        (new_allocs, skipped_keys, unplaced_asks, fallback_keys, fb_rounds).

        Asks that stopped being pending between encode and commit (released,
        placeholder-replaced or pinned mid-flight — pipelined cycles only;
        sequentially the whole cycle holds the lock) are dropped: their rows
        were invalidated at dispatch, and a stale placement must not commit
        over a consumed ask.

        node_names: the dispatch-time row→name snapshot (pipelined cycles).
        A row remapped mid-flight (node removed, row reused by a NEW node)
        must not receive the placement — the solve validated a different
        node's capacity/labels; the ask stays pending and retries next
        cycle. Sequential cycles hold the lock across solve+commit, so they
        pass None and use the live mapping."""
        new_allocs: List[Allocation] = []
        skipped_keys: List[Tuple[str, str]] = []
        unplaced_asks: List = []
        fallback_keys: List[str] = []
        fb_rounds = 0
        # commit with batched queue accounting: one ancestor walk per
        # leaf, not per allocation (matters at 50k allocations/cycle)
        # plain dict-of-int accumulators: Resource.add per alloc
        # costs a dict copy each — at 50k allocs that is measurable
        leaf_totals: Dict[str, Dict[str, int]] = {}
        # qname -> (user, groups-tuple) -> accumulator
        user_totals: Dict[str, Dict[Tuple[str, tuple], Dict[str, int]]] = {}
        limits_exist = self.queues.any_limits()
        # asks parked by locality-fallback serialization: drained in
        # intra-cycle rounds below instead of waiting a cycle per pod
        deferred_set = set(batch.deferred) if self.solver.fallback_rounds > 0 else set()
        fallback_placed: List[Tuple[object, str]] = []
        for i, ask in enumerate(admitted):
            if not self._ask_pending(ask):
                continue  # consumed mid-flight; row was invalidated
            idx = int(assigned[i])
            if idx < 0:
                if i in deferred_set:
                    continue  # retried below, same cycle
                skipped_keys.append((ask.application_id, ask.allocation_key))
                unplaced_asks.append(ask)
                continue
            node_name = self.encoder.nodes.name_of(idx)
            if node_names is not None and node_names.get(idx) != node_name:
                # row remapped since dispatch: what the solve placed on no
                # longer exists at this index — leave the ask pending
                continue
            if node_name is None:
                continue
            alloc = Allocation(
                allocation_key=ask.allocation_key,
                application_id=ask.application_id,
                node_id=node_name,
                resource=ask.resource,
                priority=ask.priority,
                placeholder=ask.placeholder,
                task_group_name=ask.task_group_name,
                tags=dict(ask.tags),
            )
            app = self._commit_allocation(alloc, credit_queue=False)
            _acc_resource(leaf_totals.setdefault(app.queue_name, {}),
                          alloc.resource)
            if limits_exist:
                _acc_resource(
                    user_totals.setdefault(app.queue_name, {}).setdefault(
                        (app.user.user, tuple(app.user.groups)), {}),
                    alloc.resource)
            if deferred_set and ask.pod is not None:
                fallback_placed.append((ask.pod, node_name))
            new_allocs.append(alloc)
        for qname, total in leaf_totals.items():
            leaf = self.queues.resolve(qname, create=False)
            if leaf is not None:
                leaf.add_allocated(Resource(total))
                if limits_exist and leaf.has_limits_in_chain():
                    for (user, groups), ut in user_totals.get(qname, {}).items():
                        leaf.add_user_allocated(user, Resource(ut), list(groups))
        if batch.locality is not None and batch.locality.fallback:
            self._m_fb_groups.inc(len(batch.locality.fallback))
        if deferred_set:
            self._m_fb_deferred.inc(len(deferred_set))
            remaining = [admitted[i] for i in sorted(deferred_set)
                         if self._ask_pending(admitted[i])]
            drained, still_blocked, fb_rounds = self._drain_locality_fallback(
                remaining, fallback_placed, node_mask, policy)
            new_allocs.extend(drained)
            fallback_keys.extend(a.allocation_key for a in drained)
            for ask in still_blocked:
                skipped_keys.append((ask.application_id, ask.allocation_key))
                unplaced_asks.append(ask)
        self._record_committed_spans([a.allocation_key for a in new_allocs],
                                     cycle_id=cycle_id)
        self._account_unschedulable(unplaced_asks)
        if self.quota_ledger is not None:
            # an admitted ask that did not commit this cycle must not keep
            # holding budget against the other shards — it re-reserves at
            # its next gate (confirmed commits already popped their
            # reservation, so this is a no-op for placed asks). Keys the
            # NEXT in-flight pipelined batch has since re-admitted keep
            # their hold: releasing here would let that batch's commit
            # fall through to the unchecked force-charge path.
            placed = {a.allocation_key for a in new_allocs}
            for ask in admitted:
                key = ask.allocation_key
                if key not in placed and key not in self._inflight_ask_keys:
                    self.quota_ledger.release_reservation(key)
        if self._evicted_for:
            # asks that placed paid their evictions off — they are no
            # longer mis-eviction candidates
            for a in new_allocs:
                self._evicted_for.pop(a.allocation_key, None)
        return new_allocs, skipped_keys, unplaced_asks, fallback_keys, fb_rounds

    PREEMPT_COOLDOWN_S = 30.0

    def _purge_preempt_cooldown(self, now: float) -> None:
        expired = [k for k, ts in self._preempted_for.items()
                   if now - ts >= self.PREEMPT_COOLDOWN_S]
        for k in expired:
            del self._preempted_for[k]
            # the ask had victims evicted for it (entry survives until the
            # ask places, _commit_solve pops it) and a whole cooldown's
            # worth of cycles still couldn't place it: those evictions were
            # wasted — the mis-eviction the SLO gates at zero
            victims = self._evicted_for.pop(k, 0)
            if victims:
                self._m_mis_evictions.inc(victims)
                logger.warning(
                    "mis-eviction: %d victim(s) evicted for ask %s which "
                    "never placed within the %.0fs cooldown", victims, k,
                    self.PREEMPT_COOLDOWN_S)

    def _app_of_pod(self) -> Dict[str, str]:
        return {
            key: app.application_id
            for app in self.partition.applications.values()
            for key in app.allocations
        }

    def _inflight_by_node(self) -> Dict[str, Resource]:
        """The solver's in-flight overlay, grouped per node (the preemption
        planners' extra_used input)."""
        out: Dict[str, Resource] = {}
        for alloc in self._inflight.values():
            cur = out.get(alloc.node_id)
            out[alloc.node_id] = (alloc.resource if cur is None
                                  else cur.add(alloc.resource))
        return out

    def _preempt_candidate_nodes(self) -> List[str]:
        """Candidate nodes in cache order, restricted to rows the encoder
        holds as schedulable — passed to BOTH planners so the device's
        node_order ranking and the host loop walk identical lists.

        With topology active the list is re-ranked toward freeing
        CONTIGUOUS ICI domains (topology/score.preempt_node_order): nodes
        in the domains holding the most free capacity come first, so victim
        selection completes nearly-open domains instead of nibbling busy
        ones. Because the single ordered list feeds both planners, the
        device/host exact-parity contract is untouched."""
        na = self.encoder.nodes
        out = []
        for name in self.cache.node_names():
            idx = na.index_of(name)
            if idx is not None and na.valid[idx] and na.schedulable[idx]:
                out.append(name)
        if self._topology_on():
            from yunikorn_tpu.topology.score import preempt_node_order

            try:
                out = preempt_node_order(out, na)
            except Exception:
                logger.exception("topology preempt ordering failed; cache "
                                 "order stands")
        return out

    def _preempt_device_enabled(self) -> bool:
        so = self.solver
        return True if so.preempt_device is None else so.preempt_device

    def _victim_credit_keys(self) -> frozenset:
        """Live cross-shard victim credits targeted at THIS shard (round
        22, ROADMAP (d)): allocation keys the fleet-wide repair pass gave
        up on, granted one eviction attempt here. Empty for the unsharded
        scheduler (no ledger) and on any ledger/RPC failure — credits are
        an optimization, never a liveness dependency."""
        ledger = self.quota_ledger
        if ledger is None:
            return frozenset()
        fn = getattr(ledger, "victim_credits", None)
        if fn is None:
            return frozenset()
        try:
            return frozenset(fn(self.shard_index))
        except Exception:
            return frozenset()

    def _preempt_dispatch(self, admitted, batch, assigned):
        """Async-dispatch the batched victim-selection solve for the rows
        the just-materialized assignment left unplaced (core lock held).
        Runs BEFORE the commit so the device computes victim prefixes while
        the host does commit bookkeeping; _plan_preemption finishes the
        handle after the commit. Returns None when preemption or the device
        planner is off, or nothing is eligible."""
        if not (self._preemption_enabled and self._preempt_device_enabled()):
            return None
        if not self.supervisor.allow("preempt"):
            # circuit open: the host planner covers this cycle outright
            # (_plan_preemption's no-handle branch); an expired cooldown
            # turned this call into the half-open probe admission
            return None
        import numpy as np

        # fast path: nothing unplaced (the overwhelmingly common cycle)
        unassigned = np.flatnonzero(
            np.asarray(assigned) < 0)
        if unassigned.size == 0:
            return None
        now = time.time()
        self._purge_preempt_cooldown(now)
        # deferred rows only "might still place" when the fallback drain
        # will actually run — same condition _commit_solve uses; with the
        # drain disabled they are ordinary unplaced asks and must ride the
        # dispatch (the residue budget cannot be allowed to starve them)
        deferred = (set(batch.deferred)
                    if self.solver.fallback_rounds > 0 else set())
        # cross-shard victim credits (round 22): a fleet-starved repaired
        # ask's credit bypasses the attempt cooldown — the fleet already
        # proved free capacity cannot hold it, so the planner may try
        # again. Credited priority<=0 asks stay off the DEVICE dispatch
        # (its victim arrays rank by real priority and would find
        # nothing); the host planner lifts them via credit_keys instead.
        credits = self._victim_credit_keys()
        prospective = []
        for i in unassigned.tolist():
            if i >= len(admitted) or i in deferred:
                continue
            ask = admitted[i]
            if not batch.valid[i] or not self._ask_pending(ask):
                continue
            if (ask.priority or 0) <= 0:
                continue
            if (ask.allocation_key in self._preempted_for
                    and ask.allocation_key not in credits):
                continue
            prospective.append(ask)
        if not prospective:
            return None
        from yunikorn_tpu.core.preemption import dispatch_preemption_solve

        use_mesh = (self._mesh is not None
                    and self.encoder.nodes.capacity % self._mesh.devices.size == 0)
        t0 = time.time()
        epoch = self.encoder.mirror_epoch
        try:
            # dispatch success alone must not re-close a half-open circuit:
            # the materialized finish is what proves the path healthy
            from yunikorn_tpu.aot import pending_enabled

            handle = self.supervisor.run(
                "preempt",
                lambda: dispatch_preemption_solve(
                    self.cache, self.encoder, prospective, self._app_of_pod(),
                    inflight_by_node=self._inflight_by_node(),
                    candidate_nodes=self._preempt_candidate_nodes(),
                    mesh=self._mesh if use_mesh else None,
                    mirror_epoch=epoch,
                    # supervised: a background-mode store miss raises
                    # CompilePending here and the host planner covers the
                    # cycle; unsupervised callers keep the inline compile
                    aot_pending=pending_enabled()),
                commit_success=False)
        except Exception:
            logger.exception("batched preemption dispatch failed; "
                             "host planner will cover this cycle")
            return None
        if handle is not None:
            handle.stats["dispatch_ms"] = (time.time() - t0) * 1000
        return handle

    def _plan_preemption(self, unplaced_asks, handle=None,
                         cycle_id=None) -> List[AllocationRelease]:
        """Preemption planning for unplaced high-priority asks (lock held).

        With a handle from _preempt_dispatch, finishes the overlapped device
        solve (every plan confirmed through the exact victim-subset search
        against the POST-commit in-flight overlay); otherwise runs the host
        planner. Plans for asks that got placed after dispatch (the
        locality-fallback drain) are dropped, not released."""
        preempt_releases: List[AllocationRelease] = []
        if not (self._preemption_enabled and unplaced_asks):
            return preempt_releases
        from yunikorn_tpu.core.preemption import (
            finish_preemption_solve,
            plan_preemptions,
        )

        t0 = time.time()
        now = t0
        self._purge_preempt_cooldown(now)
        app_of_pod = self._app_of_pod()
        inflight_by_node = self._inflight_by_node()
        credits = self._victim_credit_keys()
        stats: Dict[str, object] = {}
        if handle is not None:
            planner = "device"
            # confirmation must see capacity this cycle's commit just
            # consumed — refresh the overlay the handle captured at
            # dispatch; asks placed since dispatch (locality-fallback
            # drain) are excluded outright, so their stale plans neither
            # claim victims nor pay confirmation searches
            handle.inflight_by_node = inflight_by_node
            handle.app_of_pod = app_of_pod
            unplaced_keys = {a.allocation_key for a in unplaced_asks}
            try:
                # supervised finish: a wedged/failing materialization opens
                # the preempt circuit and this cycle re-plans on the host
                plans, attempted, stats = self.supervisor.run(
                    "preempt",
                    lambda: finish_preemption_solve(
                        handle, only_keys=unplaced_keys))
            except Exception:
                logger.exception("device preemption finish failed; "
                                 "re-planning this cycle on the host")
                handle = None
                stats = {}
        if handle is not None:
            if stats.get("fallbacks"):
                self._m_preempt_fallback.inc(stats["fallbacks"])
            # residue: unplaced asks the dispatch never saw — locality-
            # deferred rows that failed the same-cycle drain (excluded at
            # dispatch because they might still place). Host-plan them
            # against the device plans' claimed victims, inside the
            # remaining per-cycle ask budget, so a handle full of other
            # asks can never starve them of preemption.
            from yunikorn_tpu.ops.preempt import MAX_PREEMPTING_ASKS_PER_CYCLE

            handled = {a.allocation_key for a in handle.asks}
            budget = MAX_PREEMPTING_ASKS_PER_CYCLE - len(handle.asks)
            residue = [a for a in unplaced_asks
                       if a.allocation_key not in handled
                       and (a.allocation_key not in self._preempted_for
                            or a.allocation_key in credits)]
            if residue and budget > 0:
                claimed = {v.uid for p in plans for v in p.victims}
                r_plans, r_att = plan_preemptions(
                    self.cache, residue, app_of_pod, inflight_by_node,
                    candidate_nodes=handle.node_list,
                    already_victim=claimed, max_asks=budget,
                    credit_keys=credits)
                plans += r_plans
                attempted += r_att
        else:
            planner = "host"
            eligible = [a for a in unplaced_asks
                        if a.allocation_key not in self._preempted_for
                        or a.allocation_key in credits]
            plans, attempted = plan_preemptions(
                self.cache, eligible, app_of_pod, inflight_by_node,
                candidate_nodes=self._preempt_candidate_nodes(),
                credit_keys=credits)
        for key in attempted:
            # cooldown failed attempts too: an unplaceable ask must not
            # rescan the cluster every cycle
            self._preempted_for[key] = now
            if key in credits:
                # one credit buys one eviction attempt — consume it so a
                # still-unplaceable ask cannot re-scan every cycle on the
                # same grant (the repair loop may post a fresh one)
                try:
                    self.quota_ledger.consume_victim_credit(key)
                except Exception:
                    pass
        for plan in plans:
            released = 0
            for rel in plan.releases(app_of_pod):
                confirmed = self._release_allocation(rel)
                if confirmed is not None:
                    preempt_releases.append(confirmed)
                    released += 1
            if released:
                # mis-eviction ledger: victims actually evicted for this
                # ask; cleared when the ask places, counted by the cooldown
                # purge if it never does
                self._evicted_for[plan.ask.allocation_key] = (
                    self._evicted_for.get(plan.ask.allocation_key, 0)
                    + released)
        plan_ms = (time.time() - t0) * 1000 + float(stats.get("dispatch_ms", 0.0))
        if attempted or plans:
            # declared lazily at first pressure cycle: a histogram family
            # with zero children fails the exposition validator, and most
            # deployments never preempt
            self.obs.histogram(
                "preemption_plan_ms",
                "host-side preemption planning latency per pressure cycle "
                "(device = victim sync + encode + dispatch + confirm; the "
                "device solve itself overlaps the commit)",
                labelnames=("planner",), buckets=MS_BUCKETS,
            ).observe(plan_ms, planner=planner)
            self._g_preempt_last_ms.set(round(plan_ms, 3))
            # per-plan provenance: a device-branch pass can still emit
            # host plans (unsupported groups, confirmation fallbacks, the
            # residue pass) — attribute each plan by who actually made it
            for p in ("device", "host"):
                n = sum(1 for plan in plans if plan.planner == p)
                if n:
                    self._m_preempt_plans.inc(n, planner=p)
            if cycle_id is not None:
                extra = ({"compiled": stats["compiled"]}
                         if "compiled" in stats else {})
                self.tracer.add("preempt", cycle_id, t0, time.time(),
                                planner=planner, plans=len(plans),
                                victims=len(preempt_releases), **extra)
            for plan in plans:
                self._recent_preemptions.append({
                    "at": round(now, 3),
                    "cycle": cycle_id,
                    "planner": plan.planner,
                    "ask": plan.ask.allocation_key,
                    "node": plan.node_id,
                    "victims": [v.uid for v in plan.victims],
                })
        if preempt_releases:
            self._m_preempted.inc(len(preempt_releases))
            self._m_preempt_victims.inc(len(preempt_releases),
                                        reason="priority")
        return preempt_releases

    def recent_preemptions(self) -> List[dict]:
        """Last preemption plans, newest last (REST surface)."""
        with self._lock:
            return list(self._recent_preemptions)

    def _schedule_partition(self, restrict_nodes: bool = False) -> Tuple[int, tuple]:
        """One SEQUENTIAL cycle for the ACTIVE partition (core lock held);
        returns (allocation count, publish payload for _publish_cycle)."""
        t0 = time.time()
        self._cycle_seq += 1
        cid = self._cycle_seq
        self.supervisor.cycle_id = cid
        self.supervisor.policy_label = self._policy_mode()
        # unconditional cooldown purge: a wasted eviction must settle its
        # mis-eviction ledger on schedule even if this cluster never feels
        # preemption pressure again (the pressure paths also purge)
        self._purge_preempt_cooldown(t0)
        self._check_app_completion()
        self._check_placeholder_timeouts()
        replaced = self._replace_placeholders()
        pinned = self._allocate_required_node_asks()
        if pinned or replaced.new:
            # pinned/gang-replaced pods commit outside _commit_solve: close
            # their schedule spans here so their bind/e2e latency still lands
            self._record_committed_spans(
                [a.allocation_key for a in pinned]
                + [a.allocation_key for a in replaced.new])
        admitted, ranks, held = self._collect_and_gate()
        if held:
            self._m_unschedulable.inc(held, reason="quota_held")
        new_allocs: List[Allocation] = []
        skipped_keys: List[Tuple[str, str]] = []
        unplaced_asks: List = []
        fallback_keys: List[str] = []   # allocs placed by the fallback drain
        fb_rounds = 0
        preempt_handle = None
        t_gate = time.time()
        if admitted:
            # overlay BEFORE sync: an assume landing in between then counts
            # twice (once in the overlay, once in synced free) — strictly
            # conservative, never over-committing
            overlay = self._inflight_overlay()
            inflight_ports = self._inflight_ports()
            self.encoder.sync_nodes()
            # mask AFTER the sync: the encoder assigns node rows lazily
            node_mask = self._partition_node_mask() if restrict_nodes else None
            # locality counts must see in-flight allocations (committed last
            # cycle, assume not yet landed in the cache) — the locality-count
            # analog of the free/ports overlays above
            inflight_placed = self._inflight_placements()
            batch = self.encoder.build_batch_cached(admitted, ranks=ranks,
                                                    extra_placed=inflight_placed)
            self._resolve_solver_runtime()
            self._attach_device_req(admitted, batch)
            self._attach_topology(admitted, batch, overlay=overlay)
            t_encode = time.time()
            policy = self._policy_for_partition()
            handle = self._solve_dispatch(admitted, batch, policy, overlay,
                                          node_mask, inflight_ports)
            # materializing the result is the device sync point: everything
            # up to here was async dispatch; a failing/wedged tier degrades
            # and re-solves the same inputs (supervised)
            assigned = self._solve_materialize(handle)
            t_solve = time.time()
            # second-stage dispatch: the batched victim-selection solve for
            # the rows the assignment left unplaced runs on device while the
            # commit does host bookkeeping below
            preempt_handle = self._preempt_dispatch(admitted, batch, assigned)
            (new_allocs, skipped_keys, unplaced_asks, fallback_keys,
             fb_rounds) = self._commit_solve(admitted, batch, assigned,
                                             policy, node_mask, cycle_id=cid)
            self._note_topology_commit(new_allocs)
        if new_allocs or replaced.new:
            self._m_allocated.inc(len(new_allocs) + len(replaced.new))
        if skipped_keys:
            self._m_failed.inc(len(skipped_keys))
        self._m_solve_cycles.inc()
        self._m_solve_ms.inc(int((time.time() - t0) * 1000))
        t_commit = time.time()

        # preemption: try to make room for unplaced high-priority asks
        # (the batched victim solve was dispatched before the commit and
        # overlapped it; this finishes and confirms it)
        preempt_releases = self._plan_preemption(unplaced_asks,
                                                 preempt_handle, cycle_id=cid)

        # the publish payload is delivered by schedule_once AFTER the core
        # lock is released (callbacks may re-enter the core from other
        # threads; publishing under the lock risks stalls and deadlocks)
        # per-stage step timing (SURVEY §5's TPU-profiling analog: the
        # reference relies on pprof + Prometheus; here the cycle's stage
        # breakdown is the first thing a perf investigation needs). Keyed by
        # partition, stamped, and covering preemption planning ("post_ms") —
        # only cycles with admitted pods record one.
        if admitted:
            end = time.time()
            entry = {
                "at": round(end, 3),
                "pods": len(admitted),
                "gate_ms": round((t_gate - t0) * 1000, 2),
                "encode_ms": round((t_encode - t_gate) * 1000, 2),
                "solve_ms": round((t_solve - t_encode) * 1000, 2),
                "commit_ms": round((t_commit - t_solve) * 1000, 2),
                "post_ms": round((end - t_commit) * 1000, 2),
                "total_ms": round((end - t0) * 1000, 2),
                "pipelined": 0,
                "encode_cached": int(self.encoder.last_encode_cached),
                "encode_rows": self.encoder.last_encode_rows,
                "encode_reencoded": self.encoder.last_encode_rows_reencoded,
            }
            if self._last_encode_device:
                entry["encode_device_rows"] = self._last_encode_device["rows"]
                entry["encode_device_bytes"] = self._last_encode_device["bytes"]
            entry.update(_gate_extras(self._last_gate_stats))
            entry.update(_pack_extras(self._last_pack_stats))
            entry.update(_policy_extras(self._last_policy_stats))
            entry.update(_cvx_extras(self._last_cvx_stats))
            entry.update(_topo_extras(self._last_topo_stats))
            if fb_rounds:
                entry["fallback_rounds"] = fb_rounds
                entry["fallback_placed"] = len(fallback_keys)
            self._record_cycle_entry(self.partition.name, entry)
            tr = self.tracer
            pname = self.partition.name
            tr.add("gate", cid, t0, t_gate, pods=len(admitted),
                   partition=pname, **_gate_extras(self._last_gate_stats))
            tr.add("encode", cid, t_gate, t_encode,
                   cached=int(self.encoder.last_encode_cached),
                   reencoded=self.encoder.last_encode_rows_reencoded)
            tr.add("solve", cid, t_encode, t_solve,
                   policy=self._last_pack_stats.get("policy", "greedy"),
                   **_cvx_extras(self._last_cvx_stats),
                   **self._last_solve_stats)
            tr.add("commit", cid, t_solve, t_commit, allocs=len(new_allocs))
            # journey hop marks from the SAME stage stamps as the tracer
            # spans; the committed mark rides _record_committed_spans
            self._journey_cycle_marks(
                [a.allocation_key for a in admitted], t_gate, t_solve,
                self._last_gate_stats, (t_solve - t_encode) * 1000)
        return len(new_allocs), (pinned, replaced, new_allocs,
                                 preempt_releases, skipped_keys, fallback_keys)

    # ------------------------------------------------------ pipelined cycle
    # Two-stage pipeline over the same stage functions the sequential cycle
    # uses. Tick k (single scheduler thread):
    #
    #   prepare(k):   gate + encode of the NEXT batch — runs while solve k-1
    #                 is still in flight on the device (the expensive host
    #                 encode hides under the device solve)
    #   finish(k-1):  materialize (the single block_until_ready point) +
    #                 commit + preemption planning
    #   housekeeping: completion / placeholder timeouts / replacement /
    #                 pinned asks — at their sequential position (after the
    #                 previous commit, before the next dispatch)
    #   dispatch(k):  replay allocations committed since prepare(k) as a
    #                 delta (refresh_batch + the free/ports overlays),
    #                 invalidate consumed rows, async-dispatch the solve
    #   publish(k-1): RM-callback traffic (assume → bind drain) delivered
    #                 after dispatch(k), overlapping solve k's device
    #                 execution on this same thread
    #
    # Result-equivalence with the sequential cycle: the batch's pod/group
    # tensors are placement-invariant, and every placement-dependent input
    # (free capacity, ports, locality counts, fallback masks, DRA
    # serialization) is recomputed at dispatch time — i.e. strictly after
    # commit k-1, exactly the state the sequential cycle would have solved
    # against. The gate runs early with the in-flight batch charged against
    # quota (conservative: an over-held ask is re-admitted next cycle).

    def _pipeline_tick(self) -> int:
        with self._pipeline_mu:
            self._cycle_stage = "prepare"
            prep = self._pipeline_prepare()
            prev, self._pipeline_inflight = self._pipeline_inflight, None
            finished, n_prev = None, 0
            if prev is not None:
                self._cycle_stage = "finish"
                finished, n_prev = self._pipeline_finish(prev)
            extra = None
            try:
                self._cycle_stage = "housekeeping"
                extra = self._pipeline_housekeeping()
                if prep is not None:
                    self._cycle_stage = "dispatch"
                    self._pipeline_dispatch(prep)
                    self._pipeline_inflight = prep
                self._cycle_stage = "publish"
            finally:
                # publish AFTER the next solve is dispatched: the assume/
                # bind drain then runs while the device (or XLA's native
                # thread pool, which holds no GIL) executes solve k — still
                # on the scheduler thread. A separate publisher thread was
                # measured strictly worse here: the drain is Python-heavy,
                # so it fought the next cycle's encode for the GIL (2.1 s
                # encodes at 5k pods) instead of overlapping anything.
                # try/finally: cycle k-1 is already COMMITTED — a
                # housekeeping/dispatch error must not swallow its RM
                # callbacks, or the shim would never assume/bind those pods
                # (a failed dispatch leaves prep's asks pending; the next
                # gate re-admits them).
                if finished is not None:
                    t_pub0 = time.time()
                    self._publish_cycle(finished)
                    self.tracer.add("publish", prev.cycle_id, t_pub0,
                                    time.time(), allocs=n_prev)
                if extra is not None:
                    self._publish_cycle(extra)
            return n_prev

    def _drain_pipeline(self) -> None:
        """Finish a still-in-flight cycle (pipeline mutex held)."""
        prev, self._pipeline_inflight = self._pipeline_inflight, None
        if prev is None:
            return
        finished, _ = self._pipeline_finish(prev)
        if finished is not None:
            self._publish_cycle(finished)

    def _pipeline_prepare(self) -> Optional["_PipelineCycle"]:
        """Gate + encode the next batch (overlaps the in-flight solve)."""
        t0 = time.time()
        with self._lock:
            self._use_partition("default")
            if getattr(self.partition, "draining", False):
                return None
            self.supervisor.policy_label = self._policy_mode()
            admitted, ranks, held = self._collect_and_gate(
                exclude_keys=self._inflight_ask_keys or None,
                seed_admissions=self._inflight_gate_seed or None)
            if held:
                self._m_unschedulable.inc(held, reason="quota_held")
            if not admitted:
                return None
            t_gate = time.time()
            inflight_placed = self._inflight_placements()
            self.encoder.sync_nodes()
            batch = self.encoder.build_batch_cached(
                admitted, ranks=ranks, extra_placed=inflight_placed)
            self._resolve_solver_runtime_locked()
            self._attach_device_req(admitted, batch)
            self._cycle_seq += 1
            cyc = _PipelineCycle(
                cycle_id=self._cycle_seq, admitted=admitted, ranks=ranks,
                batch=batch,
                extra_fp=self.encoder.placed_fingerprint(inflight_placed),
                encode_cached=self.encoder.last_encode_cached,
                overlapped=self._pipeline_inflight is not None,
                gate_stats=dict(self._last_gate_stats),
                encode_rows=self.encoder.last_encode_rows,
                encode_reencoded=self.encoder.last_encode_rows_reencoded,
                encode_device=dict(self._last_encode_device),
                t_prepare_start=t0, t_gate=t_gate, t_encode_end=time.time())
            self.tracer.add("gate", cyc.cycle_id, t0, t_gate,
                            pods=len(admitted), **_gate_extras(cyc.gate_stats))
            self.tracer.add("encode", cyc.cycle_id, t_gate, cyc.t_encode_end,
                            cached=int(cyc.encode_cached),
                            overlapped=int(cyc.overlapped),
                            reencoded=cyc.encode_reencoded)
            jattrs = {}
            if cyc.gate_stats.get("path") is not None:
                jattrs["gate_path"] = cyc.gate_stats["path"]
            if self.quota_ledger is not None:
                r = self.quota_ledger.contention_retries
                jattrs["ledger_retries"] = r - self._ledger_retries_seen
                self._ledger_retries_seen = r
            self.journey.mark([a.allocation_key for a in admitted],
                              "gated", t_gate, **jattrs)
            return cyc

    def _pipeline_housekeeping(self) -> Optional[tuple]:
        """Commit-sensitive host work at its sequential position (post
        previous commit, pre next dispatch). Asks it consumes that are rows
        in the prepared batch are invalidated at dispatch via the
        pending-check, so nothing double-allocates."""
        with self._lock:
            self._use_partition("default")
            # unconditional: expired cooldowns must settle their
            # mis-eviction ledger even when no later cycle ever feels
            # preemption pressure (the only other purge call sites)
            self._purge_preempt_cooldown(time.time())
            self._check_app_completion()
            self._check_placeholder_timeouts()
            replaced = self._replace_placeholders()
            pinned = self._allocate_required_node_asks()
            if replaced.new:
                self._m_allocated.inc(len(replaced.new))
            if pinned or replaced.new:
                self._record_committed_spans(
                    [a.allocation_key for a in pinned]
                    + [a.allocation_key for a in replaced.new])
        if pinned or replaced.new or replaced.released:
            return (pinned, replaced, [], [], [], [])
        return None

    def _pipeline_dispatch(self, cyc: "_PipelineCycle") -> None:
        """Async-dispatch the prepared batch against post-commit state."""
        t_disp0 = time.time()
        with self._lock:
            self._use_partition("default")
            batch = cyc.batch
            # delta replay: allocations committed while this batch was being
            # encoded (previous cycle's commit, housekeeping) must reach the
            # placement-dependent state — locality counts, fallback masks,
            # DRA serialization (the free/ports overlays below carry the
            # capacity side)
            placed_now = self._inflight_placements()
            if (batch.placement_dependent
                    and self.encoder.placed_fingerprint(placed_now) != cyc.extra_fp):
                batch = self.encoder.refresh_batch(batch, cyc.admitted,
                                                   extra_placed=placed_now)
            # rows whose asks were consumed mid-encode (released, placeholder
            # replaced, pinned) leave the solve entirely
            dead = [i for i, ask in enumerate(cyc.admitted)
                    if not self._ask_pending(ask)]
            if dead:
                valid = batch.valid.copy()
                for i in dead:
                    valid[i] = False
                batch = dataclasses.replace(batch, valid=valid)
            cyc.batch = batch
            # same ordering invariant as the sequential cycle: overlay BEFORE
            # sync (conservative, never over-committing)
            overlay = self._inflight_overlay()
            inflight_ports = self._inflight_ports()
            self.encoder.sync_nodes()
            # topology fold at DISPATCH time with the same in-flight
            # overlay the solve subtracts: the domain busy/free state and
            # the gang-domain plan see exactly the capacity the fit checks
            # will see
            self._attach_topology(cyc.admitted, batch, overlay=overlay)
            cyc.policy = self._policy_for_partition()
            self._resolve_solver_runtime_locked()
            self.supervisor.cycle_id = cyc.cycle_id
            cyc.result = self._solve_dispatch(cyc.admitted, batch,
                                              cyc.policy, overlay, None,
                                              inflight_ports)
            # row→name snapshot for the commit: a row remapped while the
            # solve is in flight must not receive its placement
            cyc.node_names = dict(self.encoder.nodes._idx_to_name)
            cyc.t_dispatched = time.time()
            self.tracer.add("dispatch", cyc.cycle_id, t_disp0,
                            cyc.t_dispatched, **self._last_solve_stats)
            # mark the batch in flight: the next gate excludes these asks and
            # charges them against quota as in-cycle admissions
            self._inflight_ask_keys = {a.allocation_key for a in cyc.admitted}
            seed = []
            for ask in cyc.admitted:
                app = self.partition.applications.get(ask.application_id)
                if app is not None:
                    seed.append((app.queue_name, ask.resource,
                                 app.user.user, tuple(app.user.groups)))
            self._inflight_gate_seed = seed

    def _pipeline_finish(self, cyc: "_PipelineCycle") -> Tuple[Optional[tuple], int]:
        """Materialize + commit one in-flight cycle; returns (payload, n).

        A solve whose every tier failed (or whose deadline blew past even
        the host tier) ABANDONS the cycle instead of wedging the pipeline:
        the in-flight gate state is cleared, the asks stay pending (commit
        never ran), and the next cycle re-admits them — the failure is
        counted and lands in the health report."""
        batch = cyc.batch
        t_mat0 = time.time()
        self.supervisor.cycle_id = cyc.cycle_id
        # the device sync point — deliberately OUTSIDE the core lock so
        # informer/API threads are never stalled on device latency
        try:
            assigned = self._solve_materialize(cyc.result)
        except Exception as e:
            self._note_cycle_failure("solve", e)
            self._cycle_abandoned = True
            logger.exception("pipelined cycle %d abandoned: solve failed on "
                             "every tier", cyc.cycle_id)
            with self._lock:
                self._use_partition("default")
                self._inflight_ask_keys = set()
                self._inflight_gate_seed = []
            return None, 0
        t_mat1 = time.time()
        self.tracer.add("solve", cyc.cycle_id, cyc.t_dispatched, t_mat0,
                        policy=self._last_pack_stats.get("policy", "greedy"))
        self.tracer.add("materialize", cyc.cycle_id, t_mat0, t_mat1)
        self.journey.mark(
            [a.allocation_key for a in cyc.admitted], "solved", t_mat1,
            arm=self._last_pack_stats.get("policy", "greedy"),
            solve_ms=round((t_mat1 - cyc.t_dispatched) * 1000, 2),
            aot=self._aot_outcome())
        with self._lock:
            self._use_partition("default")
            self._inflight_ask_keys = set()
            self._inflight_gate_seed = []
            # second pipeline stage: dispatch the batched victim-selection
            # solve for the unplaced rows BEFORE the commit's host
            # bookkeeping — the device plans preemptions while the host
            # commits; _plan_preemption below confirms against post-commit
            # state
            preempt_handle = self._preempt_dispatch(cyc.admitted, batch,
                                                    assigned)
            (new_allocs, skipped_keys, unplaced_asks, fallback_keys,
             fb_rounds) = self._commit_solve(cyc.admitted, batch, assigned,
                                             cyc.policy, None,
                                             node_names=cyc.node_names,
                                             cycle_id=cyc.cycle_id)
            self._note_topology_commit(new_allocs)
            if new_allocs:
                self._m_allocated.inc(len(new_allocs))
            if skipped_keys:
                self._m_failed.inc(len(skipped_keys))
            self._m_solve_cycles.inc()
            self._m_solve_ms.inc(int(
                (time.time() - cyc.t_prepare_start) * 1000))
            t_commit = time.time()
            preempt_releases = self._plan_preemption(
                unplaced_asks, preempt_handle, cycle_id=cyc.cycle_id)
            end = time.time()
            solve_ms = (t_mat1 - cyc.t_dispatched) * 1000
            # host time between dispatch and materialization = the next
            # cycle's gate+encode (+ publish drain) hidden under this solve
            overlap_ms = max((t_mat0 - cyc.t_dispatched) * 1000, 0.0)
            entry = {
                "at": round(end, 3),
                "pods": len(cyc.admitted),
                "gate_ms": round((cyc.t_gate - cyc.t_prepare_start) * 1000, 2),
                "encode_ms": round((cyc.t_encode_end - cyc.t_gate) * 1000, 2),
                "solve_ms": round(solve_ms, 2),
                "commit_ms": round((t_commit - t_mat1) * 1000, 2),
                "post_ms": round((end - t_commit) * 1000, 2),
                "total_ms": round((end - cyc.t_prepare_start) * 1000, 2),
                "pipelined": 1,
                "encode_cached": int(cyc.encode_cached),
                "encode_rows": cyc.encode_rows,
                "encode_reencoded": cyc.encode_reencoded,
                "overlap_ms": round(overlap_ms, 2),
                "overlap_ratio": round(overlap_ms / max(solve_ms, 1e-6), 3),
            }
            if cyc.encode_device:
                entry["encode_device_rows"] = cyc.encode_device["rows"]
                entry["encode_device_bytes"] = cyc.encode_device["bytes"]
            entry.update(_gate_extras(cyc.gate_stats))
            entry.update(_pack_extras(self._last_pack_stats))
            entry.update(_policy_extras(self._last_policy_stats))
            entry.update(_cvx_extras(self._last_cvx_stats))
            entry.update(_topo_extras(self._last_topo_stats))
            if fb_rounds:
                entry["fallback_rounds"] = fb_rounds
                entry["fallback_placed"] = len(fallback_keys)
            self._record_cycle_entry(self.partition.name, entry)
            self._m_pipeline_cycles.inc()
            for k, g in self._g_pipeline.items():
                g.set(entry[k])
            self.tracer.add("commit", cyc.cycle_id, t_mat1, t_commit,
                            allocs=len(new_allocs))
        payload = ([], AllocationResponse(), new_allocs, preempt_releases,
                   skipped_keys, fallback_keys)
        return payload, len(new_allocs)

    def _publish_cycle(self, payload) -> None:
        """Deliver one partition cycle's RM-callback traffic (lock NOT held)."""
        (pinned, replaced, new_allocs, preempt_releases, skipped_keys,
         fallback_keys) = payload
        if self.callback is None:
            return
        # core event stream → shim PublishEvents (reference forwards core
        # events onto pods/nodes as K8s events, context.go:1157-1200)
        from yunikorn_tpu.common.si import EventRecord, EventRecordType

        events = [
            EventRecord(type=EventRecordType.REQUEST, object_id=a.allocation_key,
                        reference_id=a.node_id, reason="Allocated",
                        message=f"allocated on node {a.node_id}")
            for a in new_allocs[:200]  # bounded per cycle
        ]
        # operator visibility for the locality-overflow path: these pods'
        # constraints exceed the tensor encoding and took the exact
        # host-evaluated fallback (throughput: rounds, not one pod per cycle)
        fb = set(fallback_keys[:100])
        events.extend(
            EventRecord(type=EventRecordType.REQUEST, object_id=a.allocation_key,
                        reference_id=a.node_id, reason="LocalityEncodingOverflow",
                        message="constraints overflow the tensor encoding; "
                                "scheduled via exact host-path fallback")
            for a in new_allocs if a.allocation_key in fb
        )
        if events:
            self.callback.send_event(events)
        if pinned:
            self.callback.update_allocation(AllocationResponse(new=pinned))
        if replaced.new or replaced.released:
            self.callback.update_allocation(replaced)
        if new_allocs:
            self.callback.update_allocation(AllocationResponse(new=new_allocs))
        if preempt_releases:
            self.callback.update_allocation(AllocationResponse(released=preempt_releases))
        for app_id, key in skipped_keys:
            self.callback.update_container_scheduling_state(
                UpdateContainerSchedulingStateRequest(
                    application_id=app_id,
                    allocation_key=key,
                    state=ContainerSchedulingState.SKIPPED,
                    reason="insufficient cluster resources or no feasible node",
                )
            )

    def _drain_locality_fallback(self, remaining, placements, node_mask,
                                 policy) -> Tuple[List[Allocation], List, int]:
        """Same-cycle drain of locality-fallback groups (core lock held).

        Groups whose constraints overflow the tensor encoding get an exact
        host-evaluated mask that cannot see intra-batch placements, so each
        solve admits one pod per group. Instead of paying a full scheduling
        cycle per pod (the round-2 cliff: 1 pod/cycle), re-solve the parked
        remainder in small intra-cycle rounds: each round rebuilds the host
        masks with this cycle's commitments overlaid (extra_placed) and the
        inflight free-delta, so an overflowing group schedules in O(rounds).

        Returns (committed allocations, still-unplaced asks, rounds used).
        """
        so = self.solver
        committed: List[Allocation] = []
        rounds = 0
        while remaining and rounds < so.fallback_rounds:
            rounds += 1
            # same ordering invariant as the main cycle: overlay BEFORE sync.
            # The overlay picks up this cycle's commits; an assume landing in
            # between counts twice (overlay + synced free) — conservative,
            # never over-committing. Without the re-sync, an assume landing
            # mid-drain would drop its alloc from the overlay while the free
            # arrays still predate it — under-counting, over-commit.
            overlay = self._inflight_overlay()
            inflight_ports = self._inflight_ports()
            self.encoder.sync_nodes()
            batch = self.encoder.build_batch(remaining, extra_placed=placements)
            # drain rounds ride the same supervised ladder as the main solve
            # (allow_mesh=False: the drain always solves single-device, and
            # refreshing the shared mirror with a different sharding would
            # thrash the main cycle's buffers) — a failing device runtime
            # degrades the round instead of aborting a half-committed cycle
            h = self._solve_dispatch(remaining, batch, policy, overlay,
                                     node_mask, inflight_ports,
                                     allow_mesh=False)
            assigned = self._solve_materialize(h)
            progress = False
            next_remaining: List = []
            for i, ask in enumerate(remaining):
                idx = int(assigned[i])
                node_name = (self.encoder.nodes.name_of(idx) if idx >= 0
                             else None)
                if node_name is None:
                    # parked again (next group slot) or infeasible right now;
                    # feasibility can improve as siblings place, so keep it
                    # until a round makes no progress at all
                    next_remaining.append(ask)
                    continue
                alloc = Allocation(
                    allocation_key=ask.allocation_key,
                    application_id=ask.application_id,
                    node_id=node_name,
                    resource=ask.resource,
                    priority=ask.priority,
                    placeholder=ask.placeholder,
                    task_group_name=ask.task_group_name,
                    tags=dict(ask.tags),
                )
                self._commit_allocation(alloc)
                if ask.pod is not None:
                    placements.append((ask.pod, node_name))
                committed.append(alloc)
                progress = True
            if not progress:
                break
            remaining = next_remaining
        return committed, remaining, rounds

    def _allocate_required_node_asks(self) -> List[Allocation]:
        """DaemonSet-style asks pinned to one node (ask.preferred_node, the
        SI RequiredNode semantics) bypass the batched solve: verify the pin
        with the exact host predicates and allocate directly, like the core's
        required-node path."""
        from yunikorn_tpu.ops.host_predicates import pod_fits_node

        out: List[Allocation] = []
        for app in self.partition.applications.values():
            if app.state not in (APP_ACCEPTED, APP_RUNNING, APP_RESUMING):
                continue
            for key, ask in list(app.pending_asks.items()):
                if not ask.preferred_node or ask.pod is None:
                    continue
                info = self.cache.snapshot_node(ask.preferred_node)
                if info is None:
                    continue
                overlay = Resource()
                for infl in self._inflight.values():
                    if infl.node_id == ask.preferred_node:
                        overlay = overlay.add(infl.resource)
                err = pod_fits_node(ask.pod, info.node,
                                    info.available().sub(overlay), info.pods.values())
                if err is not None:
                    continue  # stays pending (preemption may free it later)
                # Pinned asks are still subject to queue headroom and
                # user/group limits (yunikorn-core gates required-node asks
                # on headroom too); hold them pending when exhausted.
                leaf = self.queues.resolve(app.queue_name, create=False)
                if leaf is not None:
                    if not leaf.fits_quota(ask.resource):
                        continue
                    if leaf.has_limits_in_chain() and not leaf.fits_user_limit(
                            app.user.user, list(app.user.groups), ask.resource):
                        continue
                alloc = Allocation(
                    allocation_key=key, application_id=app.application_id,
                    node_id=ask.preferred_node, resource=ask.resource,
                    priority=ask.priority, placeholder=ask.placeholder,
                    task_group_name=ask.task_group_name, tags=dict(ask.tags))
                self._commit_allocation(alloc)
                out.append(alloc)
        return out

    def _commit_allocation(self, alloc: Allocation, credit_queue: bool = True) -> CoreApplication:
        """Record one allocation. credit_queue=False lets the batched solve
        path aggregate queue accounting per leaf instead of per allocation."""
        app = self.partition.applications[alloc.application_id]
        app.allocations[alloc.allocation_key] = alloc
        app.pending_asks.pop(alloc.allocation_key, None)
        if not alloc.placeholder:
            app.had_real_allocation = True
        self._inflight[alloc.allocation_key] = alloc
        if app.state in (APP_ACCEPTED, APP_RESUMING):
            app.state = APP_RUNNING
        if credit_queue:
            leaf = self.queues.resolve(app.queue_name, create=False)
            if leaf is not None:
                leaf.add_allocated(alloc.resource)
                if leaf.has_limits_in_chain():
                    leaf.add_user_allocated(app.user.user, alloc.resource,
                                            list(app.user.groups))
        if self.quota_ledger is not None:
            self.quota_ledger.commit(
                alloc.allocation_key,
                self._ledger_charges_of(app, alloc.resource))
        return app

    def _cluster_capacity(self) -> Resource:
        """Total allocatable of the ACTIVE partition, memoized by the cache's
        capacity version (bumped only on node add/remove/update, not pod
        churn — 10k nodes would otherwise cost a Python reduce per cycle)."""
        # include the partition's node-membership generation: registering a
        # node into a partition changes its capacity without bumping the
        # cache's version (nodes land in the cache before core registration).
        # The partition count matters too — single-partition mode sums ALL
        # cache nodes, multi-partition filters by membership.
        gen = (self.cache.capacity_version(), self.partition.membership_gen,
               len(self.partitions) > 1)
        cached = self._cap_cache.get(self.partition.name)
        if cached is not None and cached[0] == gen:
            return cached[1]
        multi = len(self.partitions) > 1
        total: Dict[str, int] = {}
        for info in self.cache.snapshot_nodes():
            if multi and info.node.name not in self.partition.nodes:
                continue
            for k, v in info.allocatable.resources.items():
                total[k] = total.get(k, 0) + v
        cap = Resource(total)
        self._cap_cache[self.partition.name] = (gen, cap)
        return cap

    def _inflight_ports(self):
        """[capacity, Wp] u32 mask of host ports held by committed-but-not-
        yet-assumed allocations — the port analog of _inflight_overlay.
        Without it, consecutive cycles could each place a pod wanting the
        same hostPort on one node (the synthetic port columns only see
        cache-visible occupancy). Uses lookup(), not bit(): the pods'
        ports were interned when their batch was encoded."""
        import numpy as np

        from yunikorn_tpu.snapshot.vocab import port_bit

        if not self._inflight:
            return None
        out = None
        pv = self.encoder.vocabs.ports
        for key, alloc in self._inflight.items():
            pod = self.cache.get_pod(key)
            if pod is None:
                continue
            bits = []
            for c in pod.spec.containers:
                for p in c.ports:
                    hp = p.get("hostPort")
                    if hp:
                        b = pv.lookup(port_bit(p.get("protocol", "TCP"), hp))
                        if b >= 0:
                            bits.append(b)
            if not bits:
                continue
            idx = self.encoder.nodes.index_of(alloc.node_id)
            if idx is None:
                continue
            if out is None:
                out = np.zeros((self.encoder.nodes.capacity, pv.num_words),
                               np.uint32)
            for b in bits:
                out[idx, b // 32] |= np.uint32(1 << (b % 32))
        return out

    def _inflight_overlay(self):
        """[capacity, R] overlay of committed-but-not-yet-assumed allocations.

        Quantized rows are cached per allocation key (keyed to the exact
        Resource object, so a re-committed key with a new resource
        re-quantizes) and accumulated with one np.add.at gather instead of a
        per-alloc quantize_request + row add every cycle — the in-flight set
        is O(last cycle's commits), and the old loop re-quantized all of it
        every cycle."""
        import numpy as np

        drop = [k for k in self._inflight
                if self.cache.get_pod_node_name(k) is not None]
        cache_rows = self._inflight_row_cache
        for k in drop:
            self._inflight.pop(k, None)
            cache_rows.pop(k, None)
        if not self._inflight:
            if cache_rows:
                cache_rows.clear()
            return None
        if len(cache_rows) > 2 * len(self._inflight) + 64:
            # keys released through other paths leave orphans; sweep rarely
            for k in [k for k in cache_rows if k not in self._inflight]:
                cache_rows.pop(k, None)
        R = self.encoder.vocabs.resources.num_slots
        n = len(self._inflight)
        rows = np.zeros((n, R), np.float32)
        idxs = np.empty((n,), np.int64)
        count = 0
        for key, alloc in self._inflight.items():
            idx = self.encoder.nodes.index_of(alloc.node_id)
            if idx is None:
                continue
            cached = cache_rows.get(key)
            if cached is None or cached[0] is not alloc.resource:
                cached = cache_rows[key] = (
                    alloc.resource, self.encoder.quantize_request(alloc.resource))
            row = cached[1]
            # cached rows may predate vocab growth: shorter than R, never longer
            rows[count, : row.shape[0]] = row
            idxs[count] = idx
            count += 1
        overlay = np.zeros((self.encoder.nodes.capacity, R), np.float32)
        if count:
            np.add.at(overlay, idxs[:count], rows[:count])
        return overlay

    def _collect_and_gate(self, exclude_keys=None, seed_admissions=None):
        """Collect pending asks, enforce quotas, produce the global rank order.

        Ordering: queues by DRF dominant share ascending (fair share), then
        priority descending, then app submit time, then ask sequence (FIFO) —
        replicating the core's fair/fifo sort policies.

        exclude_keys: allocation keys to skip entirely — the pipelined gate
        runs while the previous batch is still in flight, and those asks'
        commits are pending. seed_admissions: [(queue, resource, user,
        groups)] of the in-flight batch, charged against quota/user limits as
        in-cycle admissions — conservatively reproducing the queue usage the
        sequential order would have committed before this gate.

        Three admission paths, tier-laddered when the device pipeline is on
        (supervised path "gate": device → cpu → host, i.e. the bounded-pass
        jitted scan (ops/gate_solve.py), the host array-form scan, the
        legacy per-ask loop): all three consume the same extracted
        GateProblem, so a degraded tier re-decides the exact same cycle.
        GateFallback (quantities the exact int64 arithmetic cannot
        represent) is raised at extraction, before any tier runs — the
        legacy loop is the authority for those cycles. All paths are pure
        w.r.t. queue-tree state, so the verify mode can run the legacy
        oracle after any of them on the same cycle.
        """
        t0 = time.perf_counter()
        cluster_cap = self._cluster_capacity()

        by_queue: Dict[str, List[Tuple[CoreApplication, object]]] = {}
        for app in self.partition.applications.values():
            if app.state not in (APP_ACCEPTED, APP_RUNNING, APP_RESUMING):
                continue
            for ask in app.pending_asks.values():
                if exclude_keys is not None and ask.allocation_key in exclude_keys:
                    continue
                by_queue.setdefault(app.queue_name, []).append((app, ask))
        if not by_queue:
            self._last_gate_stats = {}
            return [], [], 0

        meta = self._gate_queue_meta(by_queue, cluster_cap)
        admitted: Optional[List[object]] = None
        held = 0
        stats: dict = {}
        use_device = self._gate_device_on()
        use_vector = self.solver.gate_vector is not False
        problem = None
        if use_device or use_vector:
            try:
                with gate_mod.paused_gc():
                    problem = gate_mod.extract_problem(
                        by_queue, meta, self.queues, seed_admissions,
                        cache=self._gate_extract_cache)
            except GateFallback as e:
                # the cycle's quantities exceed the gate's exact int64 range
                # (or the batch its size ceiling): the loop is the authority
                logger.warning("array gate fell back to the legacy "
                               "loop: %s", e)
                self._m_gate_path.inc(path="fallback")
                stats = {"path": "legacy", "fallback": str(e)}
        if problem is not None and use_device:
            from yunikorn_tpu.ops import gate_solve

            def legacy_tier():
                adm, h = legacy_admit(by_queue, meta, self.queues,
                                      seed_admissions)
                return adm, h, {"path": "legacy"}

            tiers = [("device", lambda: gate_solve.device_admit(problem))]
            if use_vector:
                tiers.append(("cpu", lambda: gate_mod.host_scan(problem)))
            tiers.append(("host", legacy_tier))
            jc0 = gate_solve.jit_cache_entries()
            (admitted, held, stats), tier = self.supervisor.execute(
                "gate", tiers)
            jc1 = gate_solve.jit_cache_entries()
            if tier == "device" and jc0 >= 0 and jc1 > jc0:
                stats = dict(stats, compiled=True)
            self._m_gate_path.inc(path={"device": "device", "cpu": "vector",
                                        "host": "legacy"}[tier])
        elif problem is not None and use_vector:
            admitted, held, stats = gate_mod.host_scan(problem)
            self._m_gate_path.inc(path="vector")
        if admitted is None:
            if not stats:
                self._m_gate_path.inc(path="legacy")
                stats = {"path": "legacy"}
            admitted, held = legacy_admit(by_queue, meta, self.queues,
                                          seed_admissions)
        elif self.solver.gate_verify and stats.get("path") != "legacy":
            ref_admitted, ref_held = legacy_admit(by_queue, meta, self.queues,
                                                  seed_admissions)
            if (ref_held != held
                    or [a.allocation_key for a in ref_admitted]
                    != [a.allocation_key for a in admitted]):
                self._m_gate_mismatch.inc()
                logger.error(
                    "vectorized gate diverged from the legacy loop "
                    "(vector %d admitted/%d held, legacy %d/%d); "
                    "using the legacy result",
                    len(admitted), held, len(ref_admitted), ref_held)
                admitted, held = ref_admitted, ref_held
                stats = dict(stats, path="legacy", mismatch=1)
        if self.quota_ledger is not None and admitted:
            # cross-shard coupling (core/shard.GlobalQuotaLedger): the local
            # queue tree admitted against THIS shard's optimistic view; the
            # shared ledger applies the exact global check atomically. A
            # refused ask is held exactly like a quota hold — it re-enters
            # the next gate, by which time the contending shard's commit or
            # release has settled the budget.
            admitted, ledger_held = self._ledger_reserve(meta, admitted)
            if ledger_held:
                held += ledger_held
                stats["ledger_held"] = ledger_held
        if problem is not None:
            # O(changed) extraction evidence for the cycle entry/bench
            stats["extract_derived"] = self._gate_extract_cache.derived
            stats["extract_reused"] = self._gate_extract_cache.hits
        for k in ("rank_ms", "admit_ms"):
            if k in stats:
                self._m_gate_stage.observe(stats[k], stage=k[:-3])
        if stats.get("passes"):
            self._m_gate_passes.inc(int(stats["passes"]))
        stats["gate_total_ms"] = round((time.perf_counter() - t0) * 1000, 3)
        self._last_gate_stats = stats
        ranks = list(range(len(admitted)))
        return admitted, ranks, held

    # ----------------------------------------- cross-shard quota coupling
    # Active only when core/shard.ShardedCoreScheduler injected a shared
    # GlobalQuotaLedger (solver.shards >= 2). Contract: every admitted ask
    # RESERVES its limited-tracker charges before the solve; a commit
    # CONFIRMS the reservation (or force-charges for paths that commit
    # outside the gate: pinned asks, gang replacement, recovery restores);
    # an ask that finishes its cycle unplaced releases the reservation; a
    # released/evicted allocation releases its confirmed usage. With the
    # ledger unset (single shard) none of these paths execute.

    def _ledger_reserve(self, meta, admitted):
        """Reserve each admitted ask's charges on the shared ledger; asks
        the global check refuses are held (returns (kept, held_count)).
        Looks apps up per ADMITTED ask only — an O(pending) flatten of
        by_queue would put per-entity Python cost back on the gate's
        critical path.

        Hot path (round 20): the device usage mirror drains the ledger's
        commit journal ONCE per cycle and publishes pre-reduced fleet
        usage; the precheck below holds provably-over asks with zero lock
        acquisitions (the ledger would refuse them anyway — reservations
        only add to its left-hand side), and the survivors batch through
        reserve_many under ONE lock round-trip instead of one per ask.
        The ledger stays the commit-time authority throughout."""
        ledger = self.quota_ledger
        applications = self.partition.applications
        mirror = self.usage_mirror
        if mirror is not None:
            # the epoch stamp fences a quarantined zombie's late refresh
            # out of the fold (round 22; None for unsharded callers)
            mirror.refresh(self.shard_index, ledger,
                           epoch=getattr(self, "_mirror_epoch", None))
        held = 0
        pending = []
        for ask in admitted:
            app = applications.get(ask.application_id)
            charges = []
            if app is not None:
                entry = meta.get(app.queue_name)
                charges = gate_mod.ledger_charges(
                    entry[0] if entry else None, app.user.user,
                    app.user.groups, ask.resource)
            if (charges and mirror is not None
                    and mirror.provably_exceeds(charges)):
                held += 1
                continue
            pending.append((ask, charges))
        kept = []
        results = ledger.reserve_many(
            [(ask.allocation_key, charges) for ask, charges in pending])
        for (ask, _charges), ok in zip(pending, results):
            if ok:
                kept.append(ask)
            else:
                held += 1
        return kept, held

    def _ledger_charges_of(self, app, resource) -> list:
        leaf = self.queues.resolve(app.queue_name, create=False)
        return gate_mod.ledger_charges(leaf, app.user.user,
                                       app.user.groups, resource)

    def _gate_device_on(self) -> bool:
        """Tri-state solver.gateDevice resolved: auto = on (the supervisor
        ladder degrades to the host scans whenever the backend misbehaves,
        so auto does not need to probe the platform up front)."""
        return self.solver.gate_device is not False

    def _attach_device_req(self, admitted, batch) -> None:
        """Attach the device-resident req tensor (DeviceRowStore gather) to
        a built batch: a churn cycle then uploads only changed rows + an
        int32 slot index instead of the whole [N, R] req tensor, and the
        solve's pod requests never leave the device. Single-device path
        only (the mesh path replicates host arrays); supervised under the
        "encode" path so a wedged device op degrades to the host req
        instead of hanging the cycle."""
        batch.req_device = None
        self._last_encode_device = {}
        if not self._gate_device_on() or self._mesh is not None:
            return
        if not self.supervisor.allow("encode"):
            return  # circuit open: host req until a probe re-closes it
        t0 = time.perf_counter()
        try:
            batch.req_device = self.supervisor.run(
                "encode", lambda: self.encoder.device_req(admitted, batch))
        except DeadlineExceeded:
            # the zombie may still assign into the store when it unwedges:
            # orphan it (the successor starts cold, one full re-upload)
            self.encoder.row_store = None
            return
        except Exception:
            logger.exception("device req-row sync failed; host req this "
                             "cycle")
            return
        self._m_gate_stage.observe((time.perf_counter() - t0) * 1000,
                                   stage="encode")
        store = self.encoder.row_store
        if store is not None:
            self._last_encode_device = {
                "rows": store.last_upload_rows,
                "bytes": store.last_upload_bytes,
            }

    # -------------------------------------------- topology-aware placement
    # solver.topology (round 15): the ICI-domain model (topology/) steers
    # the batched score — BandPilot-style contention penalty + per-gang
    # preferred-domain plan through refined constraint groups — orders
    # preemption candidates toward freeing contiguous domains, and switches
    # the pack solver to the mesh-aligned domain-boundary partitioner. All
    # of it is score/ordering-level: with the tri-state off (or a fleet
    # with no topology labels) batch.topo stays None and every solver path
    # runs the exact pre-topology program.

    def _topology_on(self) -> bool:
        t = getattr(self.solver, "topology", None)
        if t is False:
            return False
        if t is True:
            return True
        return self.encoder.nodes.has_topology

    def _attach_topology(self, admitted, batch, overlay=None) -> None:
        """Fold the topology steering args onto the batch for this cycle's
        dispatch (core lock held, nodes synced). `overlay` is the in-flight
        allocation overlay the solve itself will subtract — the gang
        planner must see the same overlay-reduced free capacity or a
        domain filled by still-in-flight commits looks open. Scope gates
        mirror the pack solver's: locality and host-port batches keep
        their base group ids (their side tables are keyed by them)."""
        import numpy as np

        batch.topo = None
        self._last_topo_stats = {}
        self._topology_active = self._topology_on()
        if not self._topology_active:
            return
        na = self.encoder.nodes
        try:
            from yunikorn_tpu.topology import score as topo_score
            from yunikorn_tpu.topology.model import fleet_fragmentation

            if (batch.locality is None
                    and not batch.g_ports.view(np.uint32).any()):
                # domain stickiness: node rows of each batch app's EXISTING
                # allocations (O(batch apps' allocations), not O(cluster));
                # built only for batches inside the steering scope — the
                # gated ones would discard it
                app_rows: Dict[str, List[int]] = {}
                for ask in admitted[: batch.num_pods]:
                    app = self.partition.applications.get(ask.application_id)
                    if app is None or ask.application_id in app_rows:
                        continue
                    rows = []
                    for alloc in app.allocations.values():
                        idx = na.index_of(alloc.node_id)
                        if idx is not None:
                            rows.append(idx)
                    app_rows[ask.application_id] = rows
                batch.topo = topo_score.build_topo_args(
                    admitted, batch, na, app_rows, free_delta=overlay)
            if batch.topo is not None:
                s = batch.topo.stats
                frag = s["fragmentation"]
                self._last_topo_stats = {
                    "fragmentation": frag,
                    "gangs": s["gangs"], "domains": s["domains"]}
            else:
                # scope-gated or unlabeled batch: keep the gauge live from
                # a direct aggregate (build_topo_args did not run) — with
                # the SAME in-flight overlay the steered branch subtracts,
                # or the gauge jumps between batch types with no fleet
                # change
                frag = fleet_fragmentation(na, free_delta=overlay)
                self._last_topo_stats = {"fragmentation": frag}
            self._g_topo_frag.set(frag)
        except Exception:
            # steering is best-effort: a fold failure must never cost the
            # cycle — the solve runs un-steered (the topology-off program)
            batch.topo = None
            logger.exception("topology fold failed; cycle runs un-steered")

    def _note_topology_commit(self, new_allocs) -> None:
        """Commit-side gang/domain accounting: count gangs (apps placing
        >= 2 pods this cycle) and those whose placements crossed an ICI
        domain. Runs only while topology accounting is active."""
        if not self._topology_active or not new_allocs:
            return
        na = self.encoder.nodes
        doms_of_app: Dict[str, set] = {}
        for a in new_allocs:
            idx = na.index_of(a.node_id)
            dom = int(na.topo[idx, 2]) if idx is not None else -1
            doms_of_app.setdefault(a.application_id, set()).add(dom)
        counts_of_app: Dict[str, int] = {}
        for a in new_allocs:
            counts_of_app[a.application_id] = (
                counts_of_app.get(a.application_id, 0) + 1)
        gangs = cross = 0
        for app, n in counts_of_app.items():
            if n < 2:
                continue
            gangs += 1
            doms = doms_of_app[app]
            # "in one domain" = every member on the SAME labeled domain;
            # any unlabeled node or spread across domains counts as cross
            if len(doms) != 1 or -1 in doms:
                cross += 1
        if gangs:
            self._m_topo_gangs.inc(gangs)
            self._last_topo_stats["cycle_gangs"] = gangs
            self._last_topo_stats["cycle_cross_domain"] = cross
        if cross:
            self._m_topo_cross.inc(cross)

    def _gate_queue_meta(self, by_queue, cluster_cap: Resource) -> Dict[str, tuple]:
        """qname -> (leaf, dominant_share, priority_adjustment), cached.

        Leaf resolution, the DRF dominant-share walk and the priority-offset
        chain walk are pure functions of the tree's accounting epoch
        (QueueTree.version — bumped by allocation accounting, config reload
        and dynamic queue creation) and the cluster capacity; re-resolving
        every queue each gate pass was O(queues x depth) of repeated walks.
        The cache maps are extended in place on partial hits (a new queue
        name joining an unchanged tree resolves only itself)."""
        key = (id(self.queues), self.queues.version,
               tuple(sorted(cluster_cap.resources.items())))
        cached = self._gate_meta_cache
        if cached is None or cached[0] != key:
            cached = self._gate_meta_cache = (key, {})
        meta = cached[1]
        for qname in by_queue:
            if qname not in meta:
                leaf = self.queues.resolve(qname, create=False)
                meta[qname] = (
                    leaf,
                    leaf.dominant_share(cluster_cap) if leaf else 0.0,
                    leaf.priority_adjustment() if leaf else 0,
                )
        return meta

    # ------------------------------------------------------------------- gang
    def _replace_placeholders(self) -> AllocationResponse:
        """Real task asks replace Bound placeholders of the same task group.

        Core gang semantics: when an app holds placeholder allocations and a
        real (non-placeholder) ask arrives with a matching taskGroupName, the
        placeholder is released with PLACEHOLDER_REPLACED and the real
        allocation lands on the placeholder's node.
        """
        resp = AllocationResponse()
        for app in self.partition.applications.values():
            if not app.has_placeholder_allocations():
                continue
            for key, ask in list(app.pending_asks.items()):
                if ask.placeholder or not ask.task_group_name:
                    continue
                # Only replace when the real ask actually fits: within the
                # placeholder's own resource, or within the node's free plus
                # what the release returns (yunikorn-core tryPlaceholderAllocate
                # never lands a larger-than-placeholder pod without a fit
                # check). Otherwise skip — the ask goes through the batched
                # solve like any other.
                ph = None
                for cand in app.allocations.values():
                    if not cand.placeholder or cand.task_group_name != ask.task_group_name:
                        continue
                    if ask.resource.fits_in(cand.resource):
                        ph = cand
                        break
                    info = self.cache.snapshot_node(cand.node_id)
                    if info is None:
                        continue
                    # free after the release = cache-visible available, minus
                    # committed-but-not-yet-assumed allocations on the node
                    # (the placeholder itself excluded), plus the placeholder's
                    # resource when the cache already counts it as used
                    overlay = Resource()
                    for infl in self._inflight.values():
                        if (infl.node_id == cand.node_id
                                and infl.allocation_key != cand.allocation_key
                                and self.cache.get_pod_node_name(infl.allocation_key) is None):
                            overlay = overlay.add(infl.resource)
                    free_after = info.available().sub(overlay)
                    if self.cache.get_pod_node_name(cand.allocation_key) is not None:
                        free_after = free_after.add(cand.resource)
                    if ask.resource.fits_in(free_after):
                        ph = cand
                        break
                if ph is None:
                    continue
                # release placeholder
                app.allocations.pop(ph.allocation_key, None)
                if self.quota_ledger is not None:
                    self.quota_ledger.release(ph.allocation_key)
                leaf = self.queues.resolve(app.queue_name, create=False)
                if leaf is not None:
                    leaf.remove_allocated(ph.resource)
                resp.released.append(AllocationRelease(
                    application_id=app.application_id,
                    allocation_key=ph.allocation_key,
                    termination_type=TerminationType.PLACEHOLDER_REPLACED,
                    message=f"replaced by {ask.allocation_key}",
                ))
                alloc = Allocation(
                    allocation_key=ask.allocation_key,
                    application_id=app.application_id,
                    node_id=ph.node_id,
                    resource=ask.resource,
                    priority=ask.priority,
                    placeholder=False,
                    task_group_name=ask.task_group_name,
                    tags=dict(ask.tags),
                )
                self._commit_allocation(alloc)
                resp.new.append(alloc)
        return resp

    def _check_app_completion(self) -> None:
        """Running apps with no allocations and no pending asks complete after
        a grace period (yunikorn-core Completing→Completed transition); the
        shim is notified through an application status update."""
        now = time.time()
        updates: List[UpdatedApplication] = []
        for app in self.partition.applications.values():
            if app.state not in (APP_RUNNING, APP_COMPLETING, APP_RESUMING):
                continue
            if app.tags.get(SHARD_GUEST_APP_TAG):
                continue  # repair guest: the home shard owns completion
            real = any(not a.placeholder for a in app.allocations.values())
            if real or app.pending_asks:
                self._completing_since.pop(app.application_id, None)
                if app.state == APP_COMPLETING:
                    app.state = APP_RUNNING
                continue
            if app.allocations and not app.had_real_allocation:
                # gang still reserving (placeholders only, no real member ever
                # committed): the placeholder timeout owns this state
                continue
            if app.allocations:
                # workload finished; unreplaced placeholders remain — release
                # them so the gang's reserved capacity frees with the app
                # (reference application.go Completing transition)
                self._release_leftover_placeholders(app)
            since = self._completing_since.setdefault(app.application_id, now)
            if app.state == APP_RUNNING:
                app.state = APP_COMPLETING
            if now - since >= self._completing_timeout:
                app.state = APP_COMPLETED
                self._completing_since.pop(app.application_id, None)
                updates.append(UpdatedApplication(
                    application_id=app.application_id, state="Completed",
                    message="application completed"))
        if updates and self.callback is not None:
            self.callback.update_application(ApplicationResponse(updated=updates))

    def _release_leftover_placeholders(self, app) -> None:
        """Release an app's remaining placeholder allocations (workload done,
        gang floor partially unreplaced) through the standard release path —
        it owns the full bookkeeping (inflight, queue AND per-user usage);
        the shim deletes the placeholder pods on the release event."""
        leftovers = [a for a in app.allocations.values() if a.placeholder]
        released = []
        for ph in leftovers:
            rel = self._release_allocation(AllocationRelease(
                application_id=app.application_id,
                allocation_key=ph.allocation_key,
                termination_type=TerminationType.TIMEOUT,
                message="unreplaced placeholder released on app completion",
            ))
            if rel is not None:
                released.append(rel)
        if released and self.callback is not None:
            self.callback.update_allocation(AllocationResponse(released=released))

    def _check_placeholder_timeouts(self) -> None:
        """Placeholder timeout → release placeholders + app Resuming/Failing."""
        now = time.time()
        updates: List[UpdatedApplication] = []
        for app in self.partition.applications.values():
            if not app.has_placeholder_allocations() and not any(
                a.placeholder for a in app.pending_asks.values()
            ):
                continue
            if app.reserving_since is None:
                app.reserving_since = now
                continue
            timeout = app.placeholder_timeout or DEFAULT_PLACEHOLDER_TIMEOUT
            if now - app.reserving_since < timeout:
                continue
            if not any(not a.placeholder for a in app.allocations.values()):
                # no real allocations arrived before the timeout
                released = [a for a in app.allocations.values() if a.placeholder]
                for ph in released:
                    app.allocations.pop(ph.allocation_key, None)
                    if self.quota_ledger is not None:
                        self.quota_ledger.release(ph.allocation_key)
                    leaf = self.queues.resolve(app.queue_name, create=False)
                    if leaf is not None:
                        leaf.remove_allocated(ph.resource)
                for key in [k for k, a in app.pending_asks.items() if a.placeholder]:
                    app.pending_asks.pop(key, None)
                    if self.quota_ledger is not None:
                        self.quota_ledger.release(key)
                new_state = (
                    APP_FAILING if app.gang_style == constants.GANG_STYLE_HARD else APP_RESUMING
                )
                app.state = new_state
                app.reserving_since = None
                updates.append(UpdatedApplication(
                    application_id=app.application_id,
                    state=new_state,
                    message=constants.APP_FAIL_RESERVATION_TIMEOUT,
                ))
                if released and self.callback is not None:
                    self.callback.update_allocation(AllocationResponse(released=[
                        AllocationRelease(
                            application_id=app.application_id,
                            allocation_key=ph.allocation_key,
                            termination_type=TerminationType.TIMEOUT,
                            message="placeholder timeout",
                        )
                        for ph in released
                    ]))
        if updates and self.callback is not None:
            self.callback.update_application(ApplicationResponse(updated=updates))

    # ---------------------------------------------------------- observability
    @property
    def metrics(self) -> dict:
        """Legacy read surface (tests, bench, DAO): a merged snapshot of the
        registry plus the per-partition last-cycle breakdown. Read-only —
        writers go through the declared metrics on `self.obs`."""
        return self.metrics_snapshot()

    @property
    def _pipeline_trace(self):
        """Legacy tuple view of the tracer's cycle spans: the pipeline tests
        assert stage ordering on (name, cycle_id, t0, t1) tuples."""
        return [(s.name, s.cycle_id, s.t0, s.t1)
                for s in self.tracer.spans()]

    def metrics_snapshot(self) -> dict:
        """Metrics snapshot for serialization. last_cycle entries are copied
        UNDER the core lock (deep enough: the entries are flat scalar dicts),
        so a cycle publishing concurrently can never mutate a sub-dict a
        serializer is iterating — the race the old shallow `dict(metrics)`
        copy left open."""
        with self._lock:
            last = {p: dict(e) for p, e in self._last_cycle.items()}
        snap = self.obs.snapshot()
        if last:
            snap["last_cycle"] = last
        return snap

    def _note_cycle_success(self) -> None:
        now = time.time()
        self._last_cycle_success_at = now
        # a successful run-loop tick completed a cycle for EVERY live
        # partition (schedule_once iterates them; the pipelined tick is
        # single-partition mode) — a failed or abandoned tick deliberately
        # does not stamp, so the staleness objective's age grows
        for pname in list(self.partitions):
            self._cycle_done_at[pname] = now
        self._failure_streak = 0
        self._cycle_stage = None

    def _note_cycle_failure(self, stage: str, exc: BaseException) -> None:
        """One scheduling-cycle failure: counted by stage and kept as the
        health report's last-failure record (time + reason) instead of only
        swallowed into the log."""
        self._m_cycle_failures.inc(stage=stage)
        self._failure_streak += 1
        self._last_cycle_failure = {
            "at": round(time.time(), 3),
            "stage": stage,
            "reason": f"{type(exc).__name__}: {exc}"[:300],
        }
        self._cycle_stage = None

    def _scheduling_health(self) -> dict:
        """Health source: the scheduling loop itself. Liveness fails only
        when the run-loop thread died while supposed to be running; a
        failure streak (no successful cycle since) fails readiness."""
        now = time.time()
        out: dict = {
            "healthy": True,
            "last_success_age_s": round(now - self._last_cycle_success_at, 1),
            "cycles": int(self._m_solve_cycles.value()),
        }
        if self._last_cycle_failure is not None:
            out["last_failure"] = dict(self._last_cycle_failure)
        if self._failure_streak:
            out["failure_streak"] = self._failure_streak
            if self._failure_streak >= 3:
                out["healthy"] = False
        thread = self._thread
        if (self._running.is_set() and thread is not None
                and not thread.is_alive()):
            out["healthy"] = False
            out["live"] = False
            out["state"] = "loop-dead"
        return out

    def health_report(self) -> dict:
        """The /ws/v1/health payload (robustness/health.py aggregation)."""
        return self.health.report()

    def _slo_staleness(self) -> Optional[Dict[str, float]]:
        """Cycle-staleness probe (obs/slo.py): per-partition age since the
        last successfully completed run-loop cycle. None (objective not
        applicable) while the loop is not running — direct schedule_once
        callers are driving cycles by hand, and an idle test core must not
        read as a stalled production loop."""
        if not self._running.is_set():
            return None
        now = time.time()
        base = self._slo_started_at or now
        done = self._cycle_done_at
        # clamp to loop start: stamps from before a stop()/start() cycle
        # must not read as staleness the restarted loop never caused
        return {pname: now - max(done.get(pname, base), base)
                for pname in list(self.partitions)}

    def _record_cycle_entry(self, pname: str, entry: dict) -> None:
        """Publish one cycle's stage breakdown (core lock held): the
        last_cycle dict (DAO/JSON surface), the per-partition cycle_* gauges
        (Prometheus), and the stage-latency histograms (tail behavior —
        single-number gauges can't show a pipelined stage's distribution)."""
        self._last_cycle = {**self._last_cycle, pname: entry}
        self._cycle_log.append({"partition": pname, **entry})
        if self._first_cycle_ms is None and entry.get("pods"):
            # AOT cold-start objective: the first cycle that actually
            # admitted pods (idle ticks don't pay the compile/load cost
            # the budget is about)
            self._first_cycle_ms = float(entry.get("total_ms", 0.0))
            self.obs.gauge(
                "cold_first_cycle_ms",
                "wall time of this process's first scheduling cycle with "
                "admitted pods (ms) — the AOT cold-start budget's measured "
                "value; with a prebuilt store this is artifact-load + "
                "execute, without one the XLA compile stall",
            ).set(self._first_cycle_ms)
        for k, v in entry.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.obs.gauge("cycle_" + k,
                           "most recent cycle's " + k + " (per partition)",
                           labelnames=("partition",)).set(v, partition=pname)
        for k in ("gate_ms", "encode_ms", "solve_ms", "commit_ms", "post_ms",
                  "total_ms"):
            v = entry.get(k)
            if v is not None:
                self._m_cycle_stage.observe(v, stage=k[:-3],
                                            **self._stage_kw)

    # per-cycle cap on exact unplaced-ask diagnosis (a vectorized all-nodes
    # fit check per ask; the remainder is counted but not classified)
    UNSCHED_DIAG_CAP = 512
    # pod-span tracking cap: entries are popped at bind/release; the cap
    # bounds leakage from pods that never reach either (callback-less tests)
    POD_SPAN_CAP = 262144

    def _account_unschedulable(self, unplaced_asks) -> None:
        """Labelled unschedulable accounting fed from the solve's unplaced
        set (core lock held). `capacity`: no schedulable node currently has
        the free resources at all; `constraints`: capacity exists somewhere
        but predicates/conflict resolution still left the ask unplaced
        (affinity/taints/ports/locality, or it lost every accept round).
        `quota_held` asks are counted at the gate, not here."""
        if not unplaced_asks:
            return
        import numpy as np

        na = self.encoder.nodes
        ok = na.valid & na.schedulable
        free = np.floor(na.free[ok]).astype(np.int64)
        n_cap = n_con = 0
        # dedupe by quantized request row: a saturated cluster's unplaced
        # set is typically a few request SHAPES repeated thousands of times,
        # so the all-nodes fit check runs once per shape, not per ask
        shape_counts: Dict[bytes, int] = {}
        shape_rows: Dict[bytes, object] = {}
        for ask in unplaced_asks[: self.UNSCHED_DIAG_CAP]:
            row = np.ceil(self.encoder.quantize_request(
                ask.resource)).astype(np.int64)
            key = row.tobytes()
            shape_counts[key] = shape_counts.get(key, 0) + 1
            shape_rows[key] = row
        for key, n in shape_counts.items():
            row = shape_rows[key]
            if free.size and bool(
                    (free[:, : row.shape[0]] >= row).all(axis=1).any()):
                n_con += n
            else:
                n_cap += n
        if n_cap:
            self._m_unschedulable.inc(n_cap, reason="capacity")
        if n_con:
            self._m_unschedulable.inc(n_con, reason="constraints")
        rest = len(unplaced_asks) - min(len(unplaced_asks),
                                        self.UNSCHED_DIAG_CAP)
        if rest:
            self._m_unschedulable.inc(rest, reason="undiagnosed")

    def _span_submit(self, keys) -> None:
        """Open per-pod latency spans at ask arrival (submit timestamp).
        Journeys admit with the SAME `now`: the journey's admitted mark and
        the e2e span's t_submit must be one clock reading, or the stage sum
        stops tiling the measured latency. Only FRESH keys reach the
        journey — a re-sent ask keeps its original span, so it must keep
        its original admitted mark too (journey.admit would reset it)."""
        now = time.time()
        fresh = []
        with self._span_mu:
            spans = self._pod_spans
            for k in keys:
                if k not in spans and len(spans) < self.POD_SPAN_CAP:
                    spans[k] = [now, 0.0, 0]
                    fresh.append(k)
        if fresh:
            self.journey.admit(fresh, now, shard=self.shard_label)

    def _span_discard(self, key: str, outcome: Optional[str] = None) -> None:
        with self._span_mu:
            self._pod_spans.pop(key, None)
        if outcome is not None:
            self.journey.terminal(key, outcome)

    def _record_committed_spans(self, keys, cycle_id: Optional[int] = None) -> None:
        """Close the schedule half of the pod spans (submit->commit) in one
        lock round-trip + one batched histogram observation — at 50k
        allocations per cycle, per-pod locking would be measurable.

        cycle_id: the COMMITTING cycle (pipelined finish runs after prepare
        already bumped _cycle_seq, so the live counter would mis-attribute
        bind spans to the next cycle)."""
        if not keys:
            return
        cid = self._cycle_seq if cycle_id is None else cycle_id
        now = time.time()
        lats = []
        closed = []
        with self._span_mu:
            for k in keys:
                rec = self._pod_spans.get(k)
                if rec is not None and rec[1] == 0.0:
                    rec[1] = now
                    rec[2] = cid
                    lats.append(now - rec[0])
                    closed.append(k)
        if lats:
            self._m_pod_stage.observe_batch(lats, stage="schedule")
        if closed:
            # the journey's committed mark = the span's t_commit, exactly
            self.journey.mark(closed, "committed", now, cycle=cid)

    def observe_pod_bound(self, allocation_key: str) -> None:
        """Shim bind-path upcall: close the pod's end-to-end span (the bind
        is the shim's half of submit→gate→encode→solve→commit→bind). Runs on
        bind worker threads — touches the span mutex and the registry only,
        never the core lock."""
        now = time.time()
        with self._span_mu:
            rec = self._pod_spans.pop(allocation_key, None)
        if rec is None:
            return
        t_submit, t_commit, cyc = rec
        if t_commit:
            self._m_pod_stage.observe(now - t_commit, stage="bind")
            self.tracer.add_pod("bind", cyc, t_commit, now,
                                key=allocation_key)
        self._m_pod_e2e.observe(now - t_submit)
        # same `now` as the e2e observation above: journey stage sum ==
        # measured e2e, exactly (the acceptance criterion's 5% bound holds
        # with zero slack)
        self.journey.bound(allocation_key, now)

    # ------------------------------------------------------------- inspection
    def get_partition_dao(self) -> dict:
        with self._lock:
            default = self.partitions["default"]
            dao = {
                "partition": default.dao(),
                "queues": self.queue_trees["default"].dao(),
                "metrics": self.metrics_snapshot(),
            }
            if len(self.partitions) > 1:
                dao["partitions"] = {
                    name: {"partition": p.dao(), "queues": self.queue_trees[name].dao()}
                    for name, p in self.partitions.items()
                }
            return dao

    def state_dump(self) -> str:
        return json.dumps(self.get_partition_dao(), default=str)


def _pack_extras(stats: dict) -> dict:
    """Pack-comparison stats (solver.policy=optimal) for the cycle entry:
    the committed policy plus the A/B numbers when a comparison ran."""
    out = {"solver_policy": stats.get("policy", "greedy")}
    for k in ("pack_util", "pack_plan_ms", "pack_placed", "greedy_placed",
              "partitioner", "skip"):
        if k in stats:
            out["pack_skip" if k == "skip" else k] = stats[k]
    return out


def _policy_extras(stats: dict) -> dict:
    """Learned-arm stats (solver.policy=learned) for the cycle entry: util
    ratio / inference ms / checkpoint hash when the duel ran, or the skip
    reason when the arm sat out."""
    out = {}
    for k in ("learned_util", "learned_ms", "learned_placed", "checkpoint"):
        if k in stats:
            out[k] = stats[k]
    if "skip" in stats:
        out["policy_skip"] = stats["skip"]
    return out


def _cvx_extras(stats: dict) -> dict:
    """Cvx-arm stats (solver.pack=cvx, round 19) for the cycle entry: util
    ratio / solve ms / iteration budget when the duel ran, or the skip
    reason when the arm sat out."""
    out = {}
    for k in ("cvx_util", "cvx_solve_ms", "cvx_iters", "cvx_placed",
              "learned_dual"):
        if k in stats:
            out[k] = stats[k]
    if "skip" in stats:
        out["cvx_skip"] = stats["skip"]
    return out


def _topo_extras(stats: dict) -> dict:
    """Topology-fold stats (solver.topology) for the cycle entry: domain
    fragmentation plus gang-plan/commit counts when steering engaged."""
    out = {}
    for src, dst in (("fragmentation", "topo_fragmentation"),
                     ("gangs", "topo_gangs"),
                     ("domains", "topo_domains"),
                     ("cycle_gangs", "topo_cycle_gangs"),
                     ("cycle_cross_domain", "topo_cycle_cross_domain")):
        if src in stats:
            out[dst] = stats[src]
    return out


def _gate_extras(stats: dict) -> dict:
    """Gate-pass stats (core/gate.py) renamed for the cycle entry and the
    gate tracer span: path + sub-stage ms + scan-pass/tracker counts."""
    out = {}
    for src, dst in (("path", "gate_path"), ("rank_ms", "gate_rank_ms"),
                     ("admit_ms", "gate_admit_ms"), ("passes", "gate_passes"),
                     ("trackers", "gate_trackers"),
                     ("finish_loop", "gate_finish_loop"),
                     ("device_ms", "gate_device_ms"),
                     ("max_passes", "gate_max_passes"),
                     ("transfer_bytes", "gate_transfer_bytes"),
                     ("extract_derived", "gate_extract_derived"),
                     ("extract_reused", "gate_extract_reused"),
                     ("compiled", "gate_compiled")):
        if src in stats:
            v = stats[src]
            out[dst] = round(v, 3) if isinstance(v, float) else v
    return out


def _acc_resource(acc: Dict[str, int], resource: Resource) -> None:
    """Fold a resource into a plain int accumulator (Resource.add would copy
    the dict per call — measurable at 50k allocations/releases)."""
    for rk, rv in resource.resources.items():
        acc[rk] = acc.get(rk, 0) + rv


