"""Hierarchical queues with quotas and DRF fair-share.

Role-equivalent to yunikorn-core's queue subsystem (the reference shim delegates
all queue/quota decisions to it in-process; config arrives as the opaque
queues.yaml payload — reference pkg/common/utils/utils.go:368-390 passes it
through, conf keyed by policy group). This implementation keeps exact integer
Resource accounting on the host; the solver consumes the *ordering* (DRF ranks)
and the *admission* decisions (quota headroom) it produces.

queues.yaml schema (the subset the reference e2e suites exercise):

    partitions:
      - name: default
        nodesortpolicy: {type: binpacking}
        preemption: {enabled: true}
        placementrules: [...]
        queues:
          - name: root
            submitacl: "*"
            queues:
              - name: default
                resources:
                  guaranteed: {memory: 1Gi, vcore: 1}
                  max: {memory: 10Gi, vcore: 10}
                properties: {application.sort.policy: fifo}
              - name: parent
                parent: true
                queues: [...]

"vcore" maps to cpu millicores ("1" == 1000m, "100m" == 100m), matching the
core's convention.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import yaml

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.resource import Resource, parse_quantity
from yunikorn_tpu.log.logger import log

logger = log("core.queue")

ROOT = constants.ROOT_QUEUE


def _parse_res_map(m: Optional[dict]) -> Optional[Resource]:
    if not m:
        return None
    out = {}
    for k, v in m.items():
        if k in ("vcore", "cpu"):
            out["cpu"] = parse_quantity(v, as_milli=True)
        else:
            out[k] = parse_quantity(v)
    return Resource(out)


@dataclasses.dataclass
class LimitConfig:
    """Per-user/group limit inside a queue (yunikorn-core `limits:` schema,
    exercised by the reference's user_group_limit e2e suite)."""

    users: List[str] = dataclasses.field(default_factory=list)
    groups: List[str] = dataclasses.field(default_factory=list)
    max_resources: Optional[Resource] = None
    max_applications: int = 0

    def applies_to(self, user: str, user_groups: List[str]) -> bool:
        if "*" in self.users or user in self.users:
            return True
        return any(g in self.groups or "*" in self.groups for g in user_groups) \
            if self.groups else False


@dataclasses.dataclass
class QueueConfig:
    name: str
    parent: bool = False
    submit_acl: str = ""
    guaranteed: Optional[Resource] = None
    max_resource: Optional[Resource] = None
    max_applications: int = 0
    properties: Dict[str, str] = dataclasses.field(default_factory=dict)
    limits: List[LimitConfig] = dataclasses.field(default_factory=list)
    children: List["QueueConfig"] = dataclasses.field(default_factory=list)


def parse_queues_yaml(text: str, partition: str = "default") -> Optional[QueueConfig]:
    """Parse queues.yaml; returns the root QueueConfig of the partition."""
    if not text.strip():
        return None
    doc = yaml.safe_load(text)
    if not doc or "partitions" not in doc:
        return None
    for part in doc["partitions"]:
        if part.get("name", "default") != partition:
            continue
        queues = part.get("queues") or []
        for q in queues:
            if q.get("name") == ROOT:
                return _parse_queue_config(q)
    return None


def _parse_queue_config(node: dict) -> QueueConfig:
    res = node.get("resources") or {}
    limits = []
    for lim in node.get("limits") or []:
        limits.append(LimitConfig(
            users=[str(u) for u in (lim.get("users") or [])],
            groups=[str(g) for g in (lim.get("groups") or [])],
            max_resources=_parse_res_map(lim.get("maxresources")),
            max_applications=int(lim.get("maxapplications", 0) or 0),
        ))
    return QueueConfig(
        name=node.get("name", ""),
        parent=bool(node.get("parent", False)) or bool(node.get("queues")),
        submit_acl=node.get("submitacl", ""),
        guaranteed=_parse_res_map(res.get("guaranteed")),
        max_resource=_parse_res_map(res.get("max")),
        max_applications=int(node.get("maxapplications", 0) or 0),
        properties={str(k): str(v) for k, v in (node.get("properties") or {}).items()},
        limits=limits,
        children=[_parse_queue_config(c) for c in (node.get("queues") or [])],
    )


class Queue:
    """One node of the live queue tree. Exact integer accounting."""

    def __init__(self, name: str, parent: Optional["Queue"], config: Optional[QueueConfig] = None,
                 dynamic: bool = False):
        self.name = name                     # short name
        self.parent = parent
        self.children: Dict[str, Queue] = {}
        self.dynamic = dynamic               # created by placement, removable
        self.allocated = Resource()
        self.pending = Resource()
        self.app_ids: set[str] = set()
        # per-user AND per-group accounting for LimitConfig enforcement
        # (group limits cap the group's AGGREGATE usage, like yunikorn-core's
        # ugm group tracker — not each member individually)
        self.user_allocated: Dict[str, Resource] = {}
        self.user_app_counts: Dict[str, int] = {}
        self.group_allocated: Dict[str, Resource] = {}
        self.group_app_counts: Dict[str, int] = {}
        self.config = config or QueueConfig(name=name)
        # accounting/shape epoch; only the ROOT's counter is authoritative
        # (QueueTree.version) — bumped by allocation accounting, config
        # reload and dynamic queue creation so per-cycle caches of derived
        # queue state (dominant share, priority adjustment, leaf resolution)
        # can invalidate without re-walking the tree
        self.version = 0

    # ------------------------------------------------------------------ shape
    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.config.parent

    def ancestors_and_self(self) -> List["Queue"]:
        out, q = [], self
        while q is not None:
            out.append(q)
            q = q.parent
        return out

    # ------------------------------------------------------------- accounting
    def add_allocated(self, r: Resource) -> None:
        q = self
        for q in self.ancestors_and_self():
            q.allocated = q.allocated.add(r)
        q.version += 1  # q is the root after the walk

    def remove_allocated(self, r: Resource) -> None:
        q = self
        for q in self.ancestors_and_self():
            q.allocated = q.allocated.sub(r)
        q.version += 1

    def headroom(self, total_cluster: Optional[Resource] = None) -> Optional[Resource]:
        """Tightest remaining quota across self and ancestors (None = unlimited)."""
        room: Optional[Resource] = None
        for q in self.ancestors_and_self():
            if q.config.max_resource is None:
                continue
            rem = q.config.max_resource.sub(q.allocated)
            room = rem if room is None else Resource({
                k: min(room.get(k) if k in room.resources else rem.get(k), rem.get(k))
                for k in set(room.resources) | set(rem.resources)
            })
        return room

    def fits_quota(self, r: Resource) -> bool:
        """Would allocating r keep every ancestor within its max?"""
        for q in self.ancestors_and_self():
            if q.config.max_resource is not None:
                if not q.allocated.add(r).within_limit(q.config.max_resource):
                    return False
        return True

    # ---------------------------------------------------- user/group limits
    def add_user_allocated(self, user: str, r: Resource, groups: List[str] = ()) -> None:
        for q in self.ancestors_and_self():
            q.user_allocated[user] = q.user_allocated.get(user, Resource()).add(r)
            for g in groups:
                q.group_allocated[g] = q.group_allocated.get(g, Resource()).add(r)

    def remove_user_allocated(self, user: str, r: Resource, groups: List[str] = ()) -> None:
        for q in self.ancestors_and_self():
            cur = q.user_allocated.get(user)
            if cur is not None:
                q.user_allocated[user] = cur.sub(r)
            for g in groups:
                cur = q.group_allocated.get(g)
                if cur is not None:
                    q.group_allocated[g] = cur.sub(r)

    def fits_user_limit(self, user: str, groups: List[str], r: Resource,
                        cycle_extra: Optional[Dict[str, Resource]] = None) -> bool:
        """Would allocating r stay within every applicable limit up the chain?

        User-list limits check the user's own usage; group-list limits check
        the GROUP's aggregate usage. cycle_extra carries this cycle's not-yet-
        committed admissions keyed by "<queue>|u|<user>" / "<queue>|g|<group>".
        """
        ce = cycle_extra or {}
        for q in self.ancestors_and_self():
            for lim in q.config.limits:
                if lim.max_resources is None:
                    continue
                if "*" in lim.users or user in lim.users:
                    used = q.user_allocated.get(user, Resource())
                    extra = ce.get(f"{q.full_name}|u|{user}")
                    total = used.add(r) if extra is None else used.add(extra).add(r)
                    if not total.within_limit(lim.max_resources):
                        return False
                for g in groups:
                    if g in lim.groups or "*" in lim.groups:
                        used = q.group_allocated.get(g, Resource())
                        extra = ce.get(f"{q.full_name}|g|{g}")
                        total = used.add(r) if extra is None else used.add(extra).add(r)
                        if not total.within_limit(lim.max_resources):
                            return False
        return True

    def record_cycle_admission(self, user: str, groups: List[str], r: Resource,
                               cycle_extra: Dict[str, Resource]) -> None:
        """Fold an in-cycle admission into cycle_extra for every limited
        ancestor (so the cap holds across sibling leaves within one cycle)."""
        for q in self.ancestors_and_self():
            if not q.config.limits:
                continue
            key = f"{q.full_name}|u|{user}"
            cycle_extra[key] = cycle_extra.get(key, Resource()).add(r)
            for g in groups:
                key = f"{q.full_name}|g|{g}"
                cycle_extra[key] = cycle_extra.get(key, Resource()).add(r)

    def fits_user_app_limit(self, user: str, groups: List[str]) -> bool:
        """Can this user run one more application in this queue chain?"""
        for q in self.ancestors_and_self():
            for lim in q.config.limits:
                if lim.max_applications <= 0:
                    continue
                if "*" in lim.users or user in lim.users:
                    if q.user_app_counts.get(user, 0) + 1 > lim.max_applications:
                        return False
                for g in groups:
                    if g in lim.groups or "*" in lim.groups:
                        if q.group_app_counts.get(g, 0) + 1 > lim.max_applications:
                            return False
        return True

    def add_user_app(self, user: str, groups: List[str] = ()) -> None:
        for q in self.ancestors_and_self():
            q.user_app_counts[user] = q.user_app_counts.get(user, 0) + 1
            for g in groups:
                q.group_app_counts[g] = q.group_app_counts.get(g, 0) + 1

    def remove_user_app(self, user: str, groups: List[str] = ()) -> None:
        for q in self.ancestors_and_self():
            n = q.user_app_counts.get(user, 0)
            if n > 0:
                q.user_app_counts[user] = n - 1
            for g in groups:
                n = q.group_app_counts.get(g, 0)
                if n > 0:
                    q.group_app_counts[g] = n - 1

    def subtree_app_count(self) -> int:
        """Applications in this queue's subtree (parents enforce
        maxApplications over all descendants)."""
        total = len(self.app_ids)
        for child in self.children.values():
            total += child.subtree_app_count()
        return total

    def has_limits_in_chain(self) -> bool:
        return any(q.config.limits for q in self.ancestors_and_self())

    def priority_adjustment(self) -> int:
        """Queue priority offsets summed up the chain; a queue with
        priority.policy: fence stops propagation of offsets ABOVE it
        (yunikorn-core priority fence semantics)."""
        total = 0
        for q in self.ancestors_and_self():
            props = q.config.properties
            try:
                total += int(props.get("priority.offset", "0") or 0)
            except ValueError:
                pass
            if props.get("priority.policy", "").lower() == "fence":
                break
        return total

    # ------------------------------------------------------------------- ACLs
    def submit_allowed(self, user: str, groups: List[str]) -> bool:
        """submitacl semantics: "*" grants everyone; otherwise the value is
        "user1,user2 group1,group2" (space-separated user list then group
        list). ACLs are checked up the hierarchy — access granted by ANY
        ancestor suffices. Chains that define no ACL at all allow submission
        (dynamic-queue compatibility)."""
        any_defined = False
        for q in self.ancestors_and_self():
            acl = q.config.submit_acl
            if acl == "":
                continue
            any_defined = True
            if acl.strip() == "*":
                return True
            parts = acl.split(" ")
            users = [u for u in parts[0].split(",") if u] if parts else []
            acl_groups = [g for g in parts[1].split(",") if g] if len(parts) > 1 else []
            if user in users or any(g in acl_groups for g in groups):
                return True
        return not any_defined

    def dominant_share(self, cluster_capacity: Resource) -> float:
        """DRF dominant share: max over resources of allocated/denominator.

        The denominator is the queue's guaranteed amount when configured (the
        core's fair-share uses guaranteed as the fair denominator), otherwise
        the cluster capacity.
        """
        share = 0.0
        guar = self.config.guaranteed
        for name, used in self.allocated.resources.items():
            if used <= 0:
                continue
            denom = 0
            if guar is not None and guar.get(name) > 0:
                denom = guar.get(name)
            else:
                denom = cluster_capacity.get(name)
            if denom > 0:
                share = max(share, used / denom)
        return share


class QueueTree:
    """The live hierarchy + placement: resolve app queue names to leaves."""

    def __init__(self, config: Optional[QueueConfig] = None):
        self._lock = locking.RMutex()
        self.root = Queue(ROOT, None, config)
        if config is not None:
            self._build(self.root, config)

    def _build(self, q: Queue, cfg: QueueConfig) -> None:
        for child_cfg in cfg.children:
            child = Queue(child_cfg.name, q, child_cfg)
            q.children[child_cfg.name] = child
            self._build(child, child_cfg)

    def reload(self, config: Optional[QueueConfig]) -> None:
        """Hot-reload the config: update limits in place, add new queues,
        mark removed static queues dynamic (kept while they hold apps)."""
        with self._lock:
            if config is None:
                return
            self._reload_into(self.root, config)
            self.root.version += 1

    def _reload_into(self, q: Queue, cfg: QueueConfig) -> None:
        q.config = cfg
        seen = set()
        for child_cfg in cfg.children:
            seen.add(child_cfg.name)
            child = q.children.get(child_cfg.name)
            if child is None:
                child = Queue(child_cfg.name, q, child_cfg)
                q.children[child_cfg.name] = child
                self._build(child, child_cfg)
            else:
                self._reload_into(child, child_cfg)
        for name, child in q.children.items():
            if name not in seen and not child.dynamic:
                child.dynamic = True  # keep until drained

    def resolve(self, queue_name: str, create: bool = True) -> Optional[Queue]:
        """Find (or dynamically create) the leaf queue for a full name.

        Accepts "root.a.b" or "a.b" (root-relative). Returns None when the
        path crosses a static leaf or creation is disallowed.
        """
        with self._lock:
            if not queue_name:
                queue_name = f"{ROOT}.default"
            parts = queue_name.split(".")
            if parts[0] == ROOT:
                parts = parts[1:]
            q = self.root
            for i, part in enumerate(parts):
                child = q.children.get(part)
                if child is None:
                    if not create:
                        return None
                    if q.is_leaf and q is not self.root and (
                            not q.dynamic or q.app_ids or not q.allocated.is_zero()):
                        # static leaves stay leaves; an EMPTY dynamic leaf may
                        # become an intermediate (placement creates chains),
                        # but never one already hosting apps/allocations
                        logger.warning("cannot create %s under leaf queue %s", part, q.full_name)
                        return None
                    child = Queue(part, q, dynamic=True)
                    if i < len(parts) - 1:
                        child.config.parent = True  # dynamic intermediate
                    q.children[part] = child
                    self.root.version += 1
                q = child
            if not q.is_leaf:
                # app submitted to a parent queue: reject (reference behavior)
                return None
            return q

    @property
    def version(self) -> int:
        """Accounting/shape epoch of the whole tree (the root's counter):
        bumped by allocation accounting, config reload and dynamic queue
        creation. Per-cycle caches of derived queue state key on it."""
        return self.root.version

    def any_limits(self) -> bool:
        """Does ANY queue in the tree configure limits (incl. parents)?"""
        with self._lock:
            def walk(q: Queue) -> bool:
                if q.config.limits:
                    return True
                return any(walk(c) for c in q.children.values())

            return walk(self.root)

    def leaves(self) -> List[Queue]:
        with self._lock:
            out: List[Queue] = []

            def walk(q: Queue):
                if q.is_leaf:
                    out.append(q)
                for c in q.children.values():
                    walk(c)

            walk(self.root)
            return out

    def dao(self) -> dict:
        """State-dump view (REST /ws/v1/queues analog)."""
        with self._lock:
            def walk(q: Queue) -> dict:
                return {
                    "queuename": q.full_name,
                    "allocatedResource": dict(q.allocated.resources),
                    "pendingResource": dict(q.pending.resources),
                    "maxResource": dict(q.config.max_resource.resources) if q.config.max_resource else None,
                    "guaranteedResource": dict(q.config.guaranteed.resources) if q.config.guaranteed else None,
                    "isLeaf": q.is_leaf,
                    "applications": sorted(q.app_ids),
                    "children": [walk(c) for c in q.children.values()],
                }

            return walk(self.root)
