"""Placement rules: resolve an application's leaf queue.

Role-equivalent to yunikorn-core's placement-rule chain (the reference shim
feeds it queue names plus namespace tags — context.go:922-1023 adds namespace
quota/parent-queue tags; utils.go:102-118 resolves provided queue names). The
default chain (no `placementrules:` configured) matches the reference
deployment's behavior:

  1. provided      — the queue the workload named (labels/annotations)
  2. tag namespace — root.<namespace>, optionally nested under the namespace's
                     parent-queue annotation (yunikorn.apache.org/parentqueue)

With `placementrules:` in queues.yaml, the configured chain runs instead
(yunikorn-core placement semantics): rules `provided`, `user`, `group`,
`tag` (value = tag key, e.g. namespace), `fixed` (value = queue), each with
an optional allow/deny user/group `filter`, a `create` flag, and an optional
nested `parent` rule whose result prefixes the child queue.

Namespace quota/guaranteed annotations (yunikorn.apache.org/namespace.quota /
.guaranteed, JSON resource maps) become the dynamic namespace queue's limits,
exactly the reference's namespace-quota mechanism.
"""
from __future__ import annotations

import dataclasses
import json
import re
from typing import List, Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AddApplicationRequest
from yunikorn_tpu.core.queues import _parse_res_map
from yunikorn_tpu.log.logger import log

logger = log("core.placement")

_NAME_RE = re.compile(r"^[a-zA-Z0-9_-]+$")


def _sanitize_queue_part(name: str) -> str:
    """Queue-name-safe form of a user/group name (dots are hierarchy)."""
    return name.replace(".", "_dot_")


@dataclasses.dataclass
class RuleFilter:
    """allow/deny filter on the submitting user (yunikorn-core filter
    semantics: plain entries match exactly; a single non-plain entry is a
    regex matched against the whole name)."""

    type: str = ""                       # "allow" (default) or "deny"
    users: List[str] = dataclasses.field(default_factory=list)
    groups: List[str] = dataclasses.field(default_factory=list)

    def _list_matches(self, entries: List[str], names: List[str]) -> bool:
        if not entries:
            return False
        if len(entries) == 1 and not _NAME_RE.match(entries[0]):
            try:
                rx = re.compile(entries[0])
            except re.error:
                return False
            return any(rx.fullmatch(n) for n in names)
        return any(e in names for e in entries)

    def allows(self, user: str, groups: List[str]) -> bool:
        if not self.users and not self.groups:
            return True  # empty filter matches everyone
        matched = (self._list_matches(self.users, [user])
                   or self._list_matches(self.groups, list(groups)))
        return not matched if self.type == "deny" else matched


@dataclasses.dataclass
class PlacementRule:
    name: str                            # provided | user | group | tag | fixed
    create: bool = True
    value: str = ""                      # tag key (tag) / queue name (fixed)
    filter: Optional[RuleFilter] = None
    parent: Optional["PlacementRule"] = None


def parse_placement_rules(part_doc: dict) -> List[PlacementRule]:
    """Parse a partition document's `placementrules:` list (may be empty)."""

    def one(doc: dict) -> Optional[PlacementRule]:
        name = str(doc.get("name", "")).lower()
        if name not in ("provided", "user", "group", "tag", "fixed"):
            logger.warning("unknown placement rule %r ignored", name)
            return None
        filt = None
        fd = doc.get("filter") or {}
        if fd:
            filt = RuleFilter(
                type=str(fd.get("type", "")).lower(),
                users=[str(u) for u in (fd.get("users") or [])],
                groups=[str(g) for g in (fd.get("groups") or [])],
            )
        parent = None
        if doc.get("parent"):
            parent = one(doc["parent"])
        return PlacementRule(name=name, create=bool(doc.get("create", True)),
                             value=str(doc.get("value", "")),
                             filter=filt, parent=parent)

    out = []
    for doc in part_doc.get("placementrules") or []:
        rule = one(doc)
        if rule is not None:
            out.append(rule)
    return out


class PlacementEngine:
    """Run the configured rule chain; first rule yielding a queue wins
    (yunikorn-core placement manager semantics)."""

    def __init__(self, rules: List[PlacementRule]):
        self.rules = rules

    def _rule_queue(self, rule: PlacementRule, add: AddApplicationRequest) -> Optional[str]:
        user = add.user.user
        groups = list(add.user.groups)
        if rule.filter is not None and not rule.filter.allows(user, groups):
            return None
        if rule.name == "provided":
            leaf = add.queue_name
            if not leaf:
                return None
        elif rule.name == "user":
            if not user:
                return None
            leaf = _sanitize_queue_part(user)
        elif rule.name == "group":
            if not groups:
                return None
            leaf = _sanitize_queue_part(groups[0])
        elif rule.name == "tag":
            if not rule.value:
                return None
            tag = add.tags.get(rule.value)
            if not tag and rule.value == "namespace":
                tag = add.tags.get(constants.APP_TAG_NAMESPACE)
            if not tag:
                return None
            leaf = _sanitize_queue_part(tag)
        elif rule.name == "fixed":
            if not rule.value:
                return None
            leaf = rule.value
        else:
            return None

        if rule.parent is not None:
            parent_q = self._rule_queue(rule.parent, add)
            if parent_q is None:
                return None
            # a fully-qualified leaf (provided/fixed) cannot be re-parented
            if "." in leaf or leaf == constants.ROOT_QUEUE:
                return None
            return f"{parent_q}.{leaf}"
        if leaf.startswith(constants.ROOT_QUEUE + ".") or leaf == constants.ROOT_QUEUE:
            return leaf
        return f"{constants.ROOT_QUEUE}.{leaf}"

    def place(self, add: AddApplicationRequest, queues):
        """Return the first rule-resolved leaf Queue usable in `queues` (a
        QueueTree), or None; honors each rule's create flag."""
        for rule in self.rules:
            name = self._rule_queue(rule, add)
            if name is None:
                continue
            leaf = queues.resolve(name, create=rule.create)
            if leaf is not None and leaf.is_leaf:
                return leaf
        return None


def place_application(add: AddApplicationRequest) -> str:
    """Return the full queue name for an application (may not exist yet)."""
    if add.queue_name:
        return add.queue_name
    namespace = add.tags.get(constants.APP_TAG_NAMESPACE, constants.DEFAULT_APP_NAMESPACE)
    parent = add.tags.get(constants.APP_TAG_NAMESPACE_PARENT_QUEUE, "")
    if parent:
        if not parent.startswith(constants.ROOT_QUEUE):
            parent = f"{constants.ROOT_QUEUE}.{parent}"
        return f"{parent}.{namespace}"
    return f"{constants.ROOT_QUEUE}.{namespace}"


def _parse_quota_json(raw: str) -> Optional[Resource]:
    """JSON resource map → Resource via the same parser queues.yaml uses;
    malformed annotations are ignored with a warning, never raised (this runs
    inside the core's submission path)."""
    try:
        data = json.loads(raw)
        if not isinstance(data, dict):
            raise ValueError("not an object")
        return _parse_res_map(data)
    except (json.JSONDecodeError, ValueError, TypeError) as e:
        logger.warning("invalid namespace quota annotation %r: %s", raw, e)
        return None


def apply_namespace_quota(leaf, add: AddApplicationRequest) -> None:
    """Namespace quota annotations → dynamic queue limits (reference
    context.go:922-1023 / constants NamespaceQuota, NamespaceGuaranteed,
    NamespaceMaxApps). Only dynamic (placement-created) queues are adjusted —
    statically configured queues keep their yaml limits.
    """
    if not leaf.dynamic:
        return
    quota = add.tags.get(constants.NAMESPACE_QUOTA)
    if quota:
        r = _parse_quota_json(quota)
        if r is not None:
            leaf.config.max_resource = r
    guaranteed = add.tags.get(constants.NAMESPACE_GUARANTEED)
    if guaranteed:
        r = _parse_quota_json(guaranteed)
        if r is not None:
            leaf.config.guaranteed = r
    max_apps = add.tags.get(constants.NAMESPACE_MAX_APPS)
    if max_apps:
        try:
            leaf.config.max_applications = int(max_apps)
        except ValueError:
            logger.warning("invalid namespace.maxApps annotation: %r", max_apps)
