"""Placement rules: resolve an application's leaf queue.

Role-equivalent to yunikorn-core's placement-rule chain (the reference shim
feeds it queue names plus namespace tags — context.go:922-1023 adds namespace
quota/parent-queue tags; utils.go:102-118 resolves provided queue names). The
default chain matches the reference deployment's behavior:

  1. provided      — the queue the workload named (labels/annotations)
  2. tag namespace — root.<namespace>, optionally nested under the namespace's
                     parent-queue annotation (yunikorn.apache.org/parentqueue)

Namespace quota/guaranteed annotations (yunikorn.apache.org/namespace.quota /
.guaranteed, JSON resource maps) become the dynamic namespace queue's limits,
exactly the reference's namespace-quota mechanism.
"""
from __future__ import annotations

import json
from typing import Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AddApplicationRequest
from yunikorn_tpu.core.queues import _parse_res_map
from yunikorn_tpu.log.logger import log

logger = log("core.placement")


def place_application(add: AddApplicationRequest) -> str:
    """Return the full queue name for an application (may not exist yet)."""
    if add.queue_name:
        return add.queue_name
    namespace = add.tags.get(constants.APP_TAG_NAMESPACE, constants.DEFAULT_APP_NAMESPACE)
    parent = add.tags.get(constants.APP_TAG_NAMESPACE_PARENT_QUEUE, "")
    if parent:
        if not parent.startswith(constants.ROOT_QUEUE):
            parent = f"{constants.ROOT_QUEUE}.{parent}"
        return f"{parent}.{namespace}"
    return f"{constants.ROOT_QUEUE}.{namespace}"


def _parse_quota_json(raw: str) -> Optional[Resource]:
    """JSON resource map → Resource via the same parser queues.yaml uses;
    malformed annotations are ignored with a warning, never raised (this runs
    inside the core's submission path)."""
    try:
        data = json.loads(raw)
        if not isinstance(data, dict):
            raise ValueError("not an object")
        return _parse_res_map(data)
    except (json.JSONDecodeError, ValueError, TypeError) as e:
        logger.warning("invalid namespace quota annotation %r: %s", raw, e)
        return None


def apply_namespace_quota(leaf, add: AddApplicationRequest) -> None:
    """Namespace quota annotations → dynamic queue limits (reference
    context.go:922-1023 / constants NamespaceQuota, NamespaceGuaranteed,
    NamespaceMaxApps). Only dynamic (placement-created) queues are adjusted —
    statically configured queues keep their yaml limits.
    """
    if not leaf.dynamic:
        return
    quota = add.tags.get(constants.NAMESPACE_QUOTA)
    if quota:
        r = _parse_quota_json(quota)
        if r is not None:
            leaf.config.max_resource = r
    guaranteed = add.tags.get(constants.NAMESPACE_GUARANTEED)
    if guaranteed:
        r = _parse_quota_json(guaranteed)
        if r is not None:
            leaf.config.guaranteed = r
    max_apps = add.tags.get(constants.NAMESPACE_MAX_APPS)
    if max_apps:
        try:
            leaf.config.max_applications = int(max_apps)
        except ValueError:
            logger.warning("invalid namespace.maxApps annotation: %r", max_apps)
