"""Control-plane sharding: N pipelined CoreScheduler partitions behind one
SchedulerAPI front end, coupled only through an exact global quota ledger
and a stranded-ask repair pass.

The single CoreScheduler cycle is the fleet's throughput ceiling: gate,
encode, solve and commit are each device-fast, but every pod still flows
through ONE pipelined cycle loop. `ops/pack_solve.py` already proved the
POP result (arxiv 2110.11927; CvxCluster's 100-1000x claim) for one solve —
random partitioning preserves solution quality at a fraction of the cost.
This module lifts that result one level, to the control plane itself:

  ShardedCoreScheduler
      N full CoreScheduler shards, each owning a DISJOINT node partition
      (assigned by the topology partitioner below so ICI domains never
      straddle shards, re-seeded per epoch so fragmentation cannot ossify),
      each with its own pipelined cycle loop, supervised device->cpu->host
      ladder, snapshot encoder and AOT fingerprint namespace. Shards run
      their cycles concurrently on their own scheduler threads (started
      phase-staggered), so shard k's device solve overlaps shard k±1's
      host-side gate/commit.

  GlobalQuotaLedger
      The ONLY admission coupling between shards. Each shard's gate still
      admits against its local queue tree (which sees only the shard's own
      allocations — an optimistic, shard-local view); the ledger then
      applies the exact global check: reserve at admission, confirm at
      commit, release on unplaced/eviction/release. All arithmetic is
      plain-python-int exact — the same integers the gate's int64 device
      trackers carry — and atomic under one lock, so no queue max or
      user/group RESOURCE limit can be double-spent across shards. A fleet
      with no quotas configured produces zero trackers and the ledger
      costs one dict probe per ask. Known scope limit: APP-COUNT limits
      (maxApplications / per-user app counts) are still enforced per-shard
      only — cross-shard app-count coupling needs app-slot reservations on
      the registration path, a ROADMAP follow-up.

  Repair pass (stranded asks)
      Mirrors pack_solve's partition-repair contract: an ask its home
      shard reports SKIPPED re-enters scheduling on the next untried shard
      (the app is registered there as a guest) until every shard — i.e.
      the full node fleet — has seen it; only then is SKIPPED surfaced to
      the shim. A repaired ask that places clears its repair state.

  ShardCacheFanout / ShardCacheView
      All shards share ONE SchedulerCache (pods/volumes/DRA are global
      state); each shard's CoreScheduler receives a node-scoped VIEW that
      filters every node read to the shard's owned set. The fanout also
      multiplexes the cache's DESTRUCTIVE take_dirty_nodes() — N encoders
      draining it directly would steal each other's dirty marks.

`solver.shards=1` (and auto) builds a plain CoreScheduler via
make_core_scheduler — bit-identical to the pre-shard scheduler by
construction: none of the ledger/fanout/namespace hooks activate.

Differential oracle: tests/test_shard.py's shard_parity replays one trace
through 1-shard and N-shard configurations and gates on placement-quality
parity (placed count, packed units, zero ledger violations);
scripts/shard_bench.py scales the same comparison to the 10k-node bench.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

from yunikorn_tpu.common.si import (
    AllocationRequest,
    ApplicationRequest,
    NodeAction,
    NodeRequest,
    SchedulerAPI,
    UpdateContainerSchedulingStateRequest,
)
from yunikorn_tpu.common.si import NodeInfo as SiNodeInfo
from yunikorn_tpu.core.delivery import ShardDeliveryQueue
from yunikorn_tpu.core.scheduler import (
    SHARD_GUEST_APP_TAG,
    SHARD_REHOME_APP_TAG,
    CoreScheduler,
)
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.obs.flightrec import FlightRecorder, FlightRecorderOptions
from yunikorn_tpu.obs.journey import JourneyLedger
from yunikorn_tpu.obs.metrics import MS_BUCKETS, MetricsRegistry
from yunikorn_tpu.obs.trace import FRONT_PID, FleetTracer

logger = log("core.shard")

# tag the front end stamps on guest (repair-target) app registrations; the
# core skips auto-completion for guests so a drained repair target can never
# race the home shard's app lifecycle (core/scheduler._check_app_completion)
GUEST_APP_TAG = SHARD_GUEST_APP_TAG

# a reservation never confirmed within this window is presumed leaked by an
# abandoned cycle (every ordinary path releases explicitly; this is the
# failsafe so a crashed cycle cannot hold quota budget forever)
RESERVE_TTL_S = 300.0

# after a full repair round fails on every shard, the ask cools down before
# the next round so saturation does not ping-pong asks between shards every
# cycle
REPAIR_COOLDOWN_S = 10.0

# a victim credit (cross-shard preemption grant for a fleet-starved ask)
# expires unredeemed after this long — an ask that placed, died, or whose
# shard quarantined must not hold a standing eviction right
VICTIM_CREDIT_TTL_S = 60.0


# ---------------------------------------------------------------------------
# Global quota ledger
# ---------------------------------------------------------------------------
class GlobalQuotaLedger:
    """Shared exact quota/budget tracker: atomic reserve/confirm/release.

    Trackers are created lazily per charge id (see gate.ledger_charges);
    each holds plain-int per-resource `used` (confirmed allocations) and
    `reserved` (gate admissions whose commit is pending) sums. All checks
    and mutations happen under one lock — the atomicity that makes
    double-spending across concurrently-gating shards impossible."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._mu = threading.Lock()
        self._used: Dict[str, Dict[str, int]] = {}
        self._reserved: Dict[str, Dict[str, int]] = {}
        self._limits: Dict[str, Dict[str, int]] = {}   # last-seen, for audit
        # allocation_key -> list of (tracker_id, amount_items)
        self._res_by_key: Dict[str, Tuple[float, list]] = {}
        self._use_by_key: Dict[str, list] = {}
        self.reserve_held = 0          # reserves refused (ask held)
        self.contention_retries = 0    # refusals where another shard's live
        #                                reservation was part of the overage
        self.forced_charges = 0        # commits with no prior reservation
        self.expired = 0               # TTL-reaped leaked reservations
        self._m_violations = self._m_contention = None
        # confirmed-usage delta journal for the device mirror (ops/
        # ledger_mirror): every _used mutation appends (tid, items, sign);
        # the mirror drains with ONE lock-swap per refresh. None until a
        # mirror attaches — the single-shard ledger pays nothing.
        self._deltas: Optional[list] = None
        # cross-shard preemption credits: allocation_key -> (posted_at,
        # target shard) — see post_victim_credit
        self._victim_credits: Dict[str, Tuple[float, int]] = {}
        # host liveness leases: host_id -> last heartbeat; the shard
        # indices each host owns, for cross-host failover
        self._leases: Dict[str, float] = {}
        self._host_shards: Dict[str, List[int]] = {}
        if registry is not None:
            self.attach_metrics(registry)

    def attach_mirror(self, mirror) -> None:
        """Start journaling confirmed-usage deltas for `mirror` (the
        device-resident usage mirror). The ledger remains the commit-time
        authority; the mirror is a read-optimized projection."""
        mirror.bind_ledger(self)
        self.enable_journal()

    def enable_journal(self) -> None:
        """Turn on the confirmed-usage delta journal (idempotent). Split
        from attach_mirror so the RPC boundary can enable it for a REMOTE
        mirror: the LedgerClient's attach_mirror binds the mirror locally
        and sends one enable_journal op to the authority."""
        with self._mu:
            if self._deltas is not None:
                return
            self._deltas = []
            # seed with current usage so a late attach starts bit-equal
            for tid, items in self._used.items():
                vals = tuple((rk, v) for rk, v in items.items() if v)
                if vals:
                    self._deltas.append((tid, vals, 1))

    def requeue_deltas(self, deltas: list) -> None:
        """Put drained-but-unapplied deltas BACK at the journal head (in
        order, ahead of anything journaled since). The mirror's quarantine
        fence uses this: a zombie shard that drained the journal and then
        got fenced before folding must not LOSE those deltas — they
        re-drain on the next live refresh and the fold stays bit-equal."""
        if not deltas:
            return
        with self._mu:
            if self._deltas is None:
                return
            self._deltas[:0] = deltas

    def _journal_locked(self, tid: str, items, sign: int) -> None:
        if self._deltas is not None and items:
            self._deltas.append((tid, tuple(items), sign))

    def drain_deltas(self) -> list:
        """Swap out the pending confirmed-usage deltas (mirror refresh)."""
        with self._mu:
            if not self._deltas:
                return []
            out = self._deltas
            self._deltas = []
            return out

    def usage_snapshot(self) -> Dict[str, Dict[str, int]]:
        """Confirmed usage per tracker (zero entries filtered) — the host
        truth the device mirror must match bit-for-bit."""
        with self._mu:
            out: Dict[str, Dict[str, int]] = {}
            for tid, items in self._used.items():
                live = {rk: v for rk, v in items.items() if v}
                if live:
                    out[tid] = live
            return out

    def attach_metrics(self, registry: MetricsRegistry) -> None:
        self._m_violations = registry.counter(
            "shard_quota_violations_total",
            "forced ledger charges that pushed a tracker past its limit — "
            "cross-shard quota exactness is gated on this staying zero")
        self._m_contention = registry.counter(
            "shard_quota_contention_retries_total",
            "ledger reserves refused while another live reservation held "
            "part of the budget (the ask re-enters the next gate)")

    # -- internals (lock held) ---------------------------------------------
    @staticmethod
    def _add(acc: Dict[str, int], items, sign: int = 1) -> None:
        for k, v in items:
            acc[k] = acc.get(k, 0) + sign * v

    def _expire_locked(self, now: float) -> None:
        dead = [k for k, (ts, _) in self._res_by_key.items()
                if now - ts > RESERVE_TTL_S]
        for key in dead:
            _, charges = self._res_by_key.pop(key)
            for tid, amount in charges:
                self._add(self._reserved.setdefault(tid, {}), amount, -1)
            self.expired += 1
            logger.warning("quota ledger: reservation for %s expired "
                           "unconfirmed (abandoned cycle?)", key)

    # -- API ----------------------------------------------------------------
    def _reserve_locked(self, key: str, charges: list, now: float) -> bool:
        held = self._res_by_key.get(key)
        if held is not None:
            # already held (pipelined re-gate overlap): refresh the
            # stamp so a long-lived legitimate hold never TTL-expires
            self._res_by_key[key] = (now, held[1])
            return True
        if key in self._use_by_key:
            return True
        contended = False
        for tid, limit, amount in charges:
            used = self._used.get(tid, {})
            reserved = self._reserved.get(tid, {})
            self._limits[tid] = dict(limit)
            for rk, lim_v in limit:
                if (used.get(rk, 0) + reserved.get(rk, 0)
                        + dict(amount).get(rk, 0)) > lim_v:
                    if reserved.get(rk, 0) > 0:
                        contended = True
                    self.reserve_held += 1
                    if contended:
                        self.contention_retries += 1
                        if self._m_contention is not None:
                            self._m_contention.inc()
                    return False
        rec = []
        for tid, _limit, amount in charges:
            self._add(self._reserved.setdefault(tid, {}), amount)
            rec.append((tid, amount))
        self._res_by_key[key] = (now, rec)
        return True

    def reserve(self, key: str, charges: list) -> bool:
        """Atomically reserve every charge, or none. charges comes from
        gate.ledger_charges: [(tracker_id, limit_items, amount_items)].
        Empty charges (no limits configured anywhere on the chain) always
        succeed without touching tracker state."""
        if not charges:
            return True
        now = time.time()
        with self._mu:
            self._expire_locked(now)
            return self._reserve_locked(key, charges, now)

    def reserve_many(self, items: list) -> List[bool]:
        """Batched reserve: [(key, charges)] under ONE lock acquisition —
        the per-cycle gate path (core/scheduler._ledger_reserve) pays one
        lock round-trip per cycle instead of one per admitted ask.
        Sequentially exact: each entry sees the reservations the entries
        before it made, identical to N reserve() calls back-to-back."""
        if not items:
            return []
        now = time.time()
        out: List[bool] = []
        with self._mu:
            self._expire_locked(now)
            for key, charges in items:
                if not charges:
                    out.append(True)
                else:
                    out.append(self._reserve_locked(key, charges, now))
        return out

    def commit(self, key: str, charges: list) -> None:
        """Commit one allocation: confirm its reservation (the normal solve
        path), or force-charge when none exists (pinned asks, gang
        placeholder replacement, recovery restores — paths that commit
        outside the gate). Idempotent per key."""
        with self._mu:
            if key in self._use_by_key:
                # already confirmed (an idempotent re-commit) — but a LATER
                # reservation for the same key (failover re-admission racing
                # a zombie commit) must not stay held until the TTL: drop it
                rec = self._res_by_key.pop(key, None)
                if rec is not None:
                    for tid, amount in rec[1]:
                        self._add(self._reserved.setdefault(tid, {}),
                                  amount, -1)
                return
            rec = self._res_by_key.pop(key, None)
            if rec is not None:
                _, reserved = rec
                for tid, amount in reserved:
                    self._add(self._reserved.setdefault(tid, {}), amount, -1)
                    self._add(self._used.setdefault(tid, {}), amount)
                    self._journal_locked(tid, amount, 1)
                self._use_by_key[key] = reserved
                return
            if not charges:
                return
            self.forced_charges += 1
            rec2 = []
            violated = False
            for tid, limit, amount in charges:
                used = self._used.setdefault(tid, {})
                self._limits[tid] = dict(limit)
                self._add(used, amount)
                self._journal_locked(tid, amount, 1)
                rec2.append((tid, amount))
                for rk, lim_v in limit:
                    if used.get(rk, 0) > lim_v:
                        violated = True
            self._use_by_key[key] = rec2
            if violated and self._m_violations is not None:
                self._m_violations.inc()

    def release_reservation(self, key: str) -> None:
        with self._mu:
            rec = self._res_by_key.pop(key, None)
            if rec is None:
                return
            for tid, amount in rec[1]:
                self._add(self._reserved.setdefault(tid, {}), amount, -1)

    def release(self, key: str) -> None:
        """Drop whatever the key holds — reservation and/or confirmed usage
        (allocation released / evicted / app removed)."""
        with self._mu:
            rec = self._res_by_key.pop(key, None)
            if rec is not None:
                for tid, amount in rec[1]:
                    self._add(self._reserved.setdefault(tid, {}),
                              amount, -1)
            used = self._use_by_key.pop(key, None)
            if used is not None:
                for tid, amount in used:
                    self._add(self._used.setdefault(tid, {}), amount, -1)
                    self._journal_locked(tid, amount, -1)

    def audit(self) -> List[str]:
        """Tracker ids whose CONFIRMED usage exceeds the last-seen limit —
        the zero-global-quota-violations oracle the parity tests gate on."""
        out = []
        with self._mu:
            for tid, limit in self._limits.items():
                used = self._used.get(tid, {})
                for rk, lim_v in limit.items():
                    if used.get(rk, 0) > lim_v:
                        out.append(tid)
                        break
        return out

    def stats(self) -> dict:
        with self._mu:
            return {
                "trackers": len(self._limits),
                "reservations": len(self._res_by_key),
                "charged_keys": len(self._use_by_key),
                "reserve_held": self.reserve_held,
                "contention_retries": self.contention_retries,
                "forced_charges": self.forced_charges,
                "expired": self.expired,
                "victim_credits": len(self._victim_credits),
                "host_leases": len(self._leases),
            }

    # -- victim credits (ROADMAP (d): cross-shard preemption) ---------------
    def post_victim_credit(self, key: str, shard: int) -> None:
        """A starved repaired ask (every shard's repair gate refused it)
        posts a credit against the shard it stays pending on: that shard's
        preemption planner may now EVICT for it instead of only repairing
        onto free capacity. TTL-bounded so a credit for an ask that later
        places (or dies) cannot linger forever."""
        with self._mu:
            self._victim_credits[key] = (time.time(), int(shard))

    def victim_credits(self, shard: int) -> List[str]:
        """Live credit keys targeted at `shard` (expired entries reaped)."""
        now = time.time()
        with self._mu:
            dead = [k for k, (ts, _s) in self._victim_credits.items()
                    if now - ts > VICTIM_CREDIT_TTL_S]
            for k in dead:
                del self._victim_credits[k]
            return [k for k, (_ts, s) in self._victim_credits.items()
                    if s == int(shard)]

    def consume_victim_credit(self, key: str) -> bool:
        """Pop the credit when the planner actually attempts eviction for
        it — one credit buys one preemption attempt, not a standing right."""
        with self._mu:
            return self._victim_credits.pop(key, None) is not None

    def clear_victim_credit(self, key: str) -> None:
        """Drop a credit whose ask no longer needs it (placed/forgotten)."""
        with self._mu:
            self._victim_credits.pop(key, None)

    # -- host leases (ROADMAP (e): ledger as liveness authority) ------------
    def register_host_shards(self, host: str, shards: List[int]) -> None:
        """Declare which shard indices `host` owns and start its lease."""
        with self._mu:
            self._host_shards[host] = [int(s) for s in shards]
            self._leases[host] = time.time()

    def heartbeat_host(self, host: str) -> None:
        with self._mu:
            if host in self._leases:
                self._leases[host] = time.time()

    def expired_hosts(self, ttl_s: float) -> List[Tuple[str, List[int]]]:
        """Hosts whose lease aged past `ttl_s`, POPPED so each expiry fires
        exactly once (the surviving supervisor that observes it owns the
        quarantine; a re-registered host starts a fresh lease)."""
        now = time.time()
        out: List[Tuple[str, List[int]]] = []
        with self._mu:
            dead = [h for h, ts in self._leases.items()
                    if now - ts > ttl_s]
            for h in dead:
                del self._leases[h]
                out.append((h, self._host_shards.pop(h, [])))
        return out

    def host_leases(self) -> Dict[str, float]:
        """Seconds since each live host's last heartbeat (diagnostics)."""
        now = time.time()
        with self._mu:
            return {h: now - ts for h, ts in self._leases.items()}


# ---------------------------------------------------------------------------
# Shared-cache fan-out: one SchedulerCache, N node-scoped views
# ---------------------------------------------------------------------------
class ShardCacheFanout:
    """Owns the node->shard map and multiplexes the base cache's destructive
    take_dirty_nodes() into per-shard pending sets. Marks for nodes with no
    owner yet (informer events racing core registration) are parked and
    flushed to the owner the moment one is assigned."""

    def __init__(self, cache, n_shards: int):
        self.cache = cache
        self.n = n_shards
        self._mu = threading.Lock()
        self._owner: Dict[str, int] = {}
        # per-shard owned-name sets: names_for/count_for are O(owned), not
        # an O(fleet) owner-map scan (the repair pass sizes every untried
        # shard per stranded ask — under the front _mu)
        self._owned: List[Set[str]] = [set() for _ in range(n_shards)]
        self._pending: List[Tuple[Set[str], Set[str]]] = [
            (set(), set()) for _ in range(n_shards)]
        self._unowned: Tuple[Set[str], Set[str]] = (set(), set())
        self._membership = [0] * n_shards

    def owner_of(self, name: str) -> Optional[int]:
        with self._mu:
            return self._owner.get(name)

    def set_owner(self, name: str, idx: Optional[int]) -> None:
        """Assign/move/drop a node's owning shard. Both the old and new
        owner get an object-dirty mark so the next syncs remove/create the
        row; parked unowned marks flush to a new owner."""
        with self._mu:
            old = self._owner.get(name)
            if old == idx:
                return
            if old is not None:
                self._pending[old][0].add(name)
                self._pending[old][1].add(name)
                self._membership[old] += 1
                self._owned[old].discard(name)
            if idx is None:
                self._owner.pop(name, None)
            else:
                self._owner[name] = idx
                self._owned[idx].add(name)
                self._pending[idx][0].add(name)
                self._pending[idx][1].add(name)
                self._membership[idx] += 1
                self._unowned[0].discard(name)
                self._unowned[1].discard(name)

    def membership_version(self, idx: int) -> int:
        with self._mu:
            return self._membership[idx]

    def take_dirty(self, idx: int) -> Tuple[Set[str], Set[str]]:
        """Drain the base cache's dirty sets, distribute by ownership, then
        return-and-clear this shard's accumulated marks."""
        with self._mu:
            dirty, objects = self.cache.take_dirty_nodes()
            for name in dirty:
                o = self._owner.get(name)
                tgt = self._pending[o] if o is not None else self._unowned
                tgt[0].add(name)
                if name in objects:
                    tgt[1].add(name)
            d, ob = self._pending[idx]
            self._pending[idx] = (set(), set())
            return d, ob

    def names_for(self, idx: int) -> List[str]:
        with self._mu:
            return list(self._owned[idx])

    def count_for(self, idx: int) -> int:
        with self._mu:
            return len(self._owned[idx])


class ShardCacheView:
    """Node-scoped view of the shared SchedulerCache for one shard's
    CoreScheduler + SnapshotEncoder: node reads filter to the shard's owned
    set, everything else (pods, volumes, DRA, priority classes, generations)
    delegates to the base cache."""

    def __init__(self, fanout: ShardCacheFanout, idx: int):
        self._fanout = fanout
        self._idx = idx
        self.base = fanout.cache

    def __getattr__(self, name):
        return getattr(self.base, name)

    # -- node-scoped overrides ---------------------------------------------
    def _owned(self, name: str) -> bool:
        return self._fanout.owner_of(name) == self._idx

    def get_node(self, name: str):
        return self.base.get_node(name) if self._owned(name) else None

    def snapshot_node(self, name: str):
        return self.base.snapshot_node(name) if self._owned(name) else None

    def node_names(self) -> List[str]:
        return self._fanout.names_for(self._idx)

    def node_count(self) -> int:
        return self._fanout.count_for(self._idx)

    def snapshot_nodes(self) -> list:
        own = set(self._fanout.names_for(self._idx))
        return [info for info in self.base.snapshot_nodes()
                if info.node.name in own]

    def take_dirty_nodes(self) -> Tuple[Set[str], Set[str]]:
        return self._fanout.take_dirty(self._idx)

    def capacity_version(self):
        # the shard's capacity changes when EITHER a node object changes or
        # shard membership moves a node; equality-keyed memo consumers
        # (CoreScheduler._cluster_capacity) accept any hashable
        return (self.base.capacity_version(),
                self._fanout.membership_version(self._idx))


# ---------------------------------------------------------------------------
# Topology-aware node partitioner (ICI domains never straddle shards)
# ---------------------------------------------------------------------------
class ShardTopologyPartitioner:
    """Deterministic domain->shard assignment: every node of one ICI domain
    lands in one shard, domains balance across shards by count, and the
    epoch seed rotates the placement so one epoch's fragmentation cannot
    ossify into the next. Unlabeled nodes form singleton domains keyed by
    node name."""

    def __init__(self, n_shards: int, seed: int = 0):
        self.n = n_shards
        self.seed = seed
        self.domain_shard: Dict[tuple, int] = {}
        self.domain_nodes: Dict[tuple, Set[str]] = {}
        self.node_domain: Dict[str, tuple] = {}
        self._counts = [0] * n_shards
        # failure domains: a quarantined shard is inactive — _pick (and so
        # assign/reseed/evacuate) never target it until it rejoins
        self.active = [True] * n_shards

    @staticmethod
    def domain_of(name: str, labels: Optional[Dict[str, str]]) -> tuple:
        from yunikorn_tpu.topology.model import (normalize_topology_labels,
                                                 parse_topology_labels)

        if labels:
            _sl, _rack, ici = parse_topology_labels(
                normalize_topology_labels(labels))
            if ici is not None:
                return ("ici",) + tuple(ici)
        return ("node", name)

    def _pick(self, dom: tuple, seed: int) -> int:
        base = zlib.crc32(f"{seed}:{dom}".encode()) % self.n
        cands = [k for k in range(self.n) if self.active[k]]
        if not cands:  # nothing active: degenerate, keep determinism
            cands = list(range(self.n))
        return min(cands,
                   key=lambda k: (self._counts[k], (k - base) % self.n))

    def set_active(self, idx: int, active: bool) -> None:
        self.active[idx] = bool(active)

    def evacuate(self, idx: int) -> Dict[str, Tuple[int, int]]:
        """Move every domain owned by shard `idx` onto the active shards
        (the quarantine re-home). Whole domains move — the never-straddle
        invariant survives failover. Deterministic: domains revisited in
        sorted order under the current seed. Returns {node: (old, new)}.
        The caller marks `idx` inactive first."""
        moves: Dict[str, Tuple[int, int]] = {}
        for dom in sorted(d for d, s in self.domain_shard.items()
                          if s == idx):
            self._counts[idx] -= 1
            new = self._pick(dom, self.seed)
            self.domain_shard[dom] = new
            self._counts[new] += 1
            for name in self.domain_nodes.get(dom, ()):
                moves[name] = (idx, new)
        return moves

    def assign(self, name: str, labels: Optional[Dict[str, str]]) -> int:
        dom = self.domain_of(name, labels)
        prev = self.node_domain.get(name)
        if prev is not None and prev != dom:
            # re-registration with CHANGED topology labels: drop the stale
            # domain membership first, or reseed() would keep acting on it
            # (migrating the node with its OLD domain — splitting it from
            # its actual siblings) and _counts would drift
            self.remove(name)
        self.node_domain[name] = dom
        self.domain_nodes.setdefault(dom, set()).add(name)
        shard = self.domain_shard.get(dom)
        if shard is None:
            shard = self._pick(dom, self.seed)
            self.domain_shard[dom] = shard
            self._counts[shard] += 1
        return shard

    def remove(self, name: str) -> None:
        dom = self.node_domain.pop(name, None)
        if dom is None:
            return
        nodes = self.domain_nodes.get(dom)
        if nodes is not None:
            nodes.discard(name)
            if not nodes:
                del self.domain_nodes[dom]
                shard = self.domain_shard.pop(dom, None)
                if shard is not None:
                    self._counts[shard] -= 1

    def reseed(self, seed: int) -> Dict[str, Tuple[int, int]]:
        """Recompute the whole assignment under a new seed; returns
        {node: (old_shard, new_shard)} for every node that moves.
        Deterministic: domains are revisited in sorted order."""
        self.seed = seed
        old = dict(self.domain_shard)
        self.domain_shard = {}
        self._counts = [0] * self.n
        moves: Dict[str, Tuple[int, int]] = {}
        for dom in sorted(self.domain_nodes):
            shard = self._pick(dom, seed)
            self.domain_shard[dom] = shard
            self._counts[shard] += 1
            prev = old.get(dom)
            if prev is not None and prev != shard:
                for name in self.domain_nodes[dom]:
                    moves[name] = (prev, shard)
        return moves


# ---------------------------------------------------------------------------
# Per-shard callback: fan-in + repair interception
# ---------------------------------------------------------------------------
class _ShardCallback:
    """Wraps the real RM callback for one shard: passes responses through,
    tees per-shard accounting into the front end, intercepts SKIPPED for
    the stranded-ask repair pass, and suppresses app-Completed updates the
    home shard cannot decide alone (repaired allocations live elsewhere)."""

    def __init__(self, front: "ShardedCoreScheduler", idx: int, real):
        self._front = front
        self._idx = idx
        self._real = real
        # fenced at quarantine: a wedged cycle that finally unwedges AFTER
        # its shard was quarantined must not leak zombie commits/releases
        # into the fleet view — the shard's asks were re-admitted and its
        # nodes re-homed while it was stuck (predicates stay answerable:
        # the zombie thread blocks on their return value)
        self.dead = False

    def update_allocation(self, response) -> None:
        if self.dead:
            return
        if response.new or response.released:
            self._front._note_allocations(self._idx, response)
        if response.rejected:
            # a rejected ask gets no release event: forget its routing
            # entries here or _asks/_ask_home leak for the process lifetime
            self._front._forget_asks(
                [(r.application_id, r.allocation_key)
                 for r in response.rejected])
        self._real.update_allocation(response)

    def update_application(self, response) -> None:
        if self.dead:
            return
        response = self._front._filter_app_updates(self._idx, response)
        if response is not None:
            self._real.update_application(response)

    def update_node(self, response) -> None:
        if not self.dead:
            self._real.update_node(response)

    def update_container_scheduling_state(self, request) -> None:
        if self.dead:
            return
        if request.state and str(request.state).endswith("SKIPPED"):
            if self._front._on_skipped(self._idx, request):
                return  # repair in flight: not yet unschedulable
        self._real.update_container_scheduling_state(request)

    def predicates(self, args):
        return self._real.predicates(args)

    def preemption_predicates(self, args):
        return self._real.preemption_predicates(args)

    def send_event(self, events) -> None:
        if not self.dead:
            self._real.send_event(events)

    def __getattr__(self, name):
        return getattr(self._real, name)


# ---------------------------------------------------------------------------
# Facades (REST/replay compatibility surfaces)
# ---------------------------------------------------------------------------
class _ShardSlo:
    """SLO facade: ticks/resets fan out to every shard's engine; the report
    comes from the first ACTIVE shard (all engines consume the same shared
    e2e stream, but a quarantined shard's engine is detached and frozen);
    violations merge as the per-objective MAX across shards (one stalled
    shard must surface, N engines seeing the same e2e episode must not
    count it N times)."""

    def __init__(self, shards: List[CoreScheduler], front=None):
        self._shards = shards
        self._front = front

    def maybe_tick(self) -> None:
        for core in self._shards:
            core.slo.maybe_tick()

    def tick(self, now=None):
        out = None
        for core in self._shards:
            out = core.slo.tick(now)
        return out

    def reset(self) -> None:
        for core in self._shards:
            core.slo.reset()

    def violations(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for core in self._shards:
            for k, v in core.slo.violations().items():
                out[k] = max(out.get(k, 0), v)
        return out

    def report(self) -> dict:
        quarantined = (self._front._quarantined
                       if self._front is not None else set())
        for k, core in enumerate(self._shards):
            if k not in quarantined:
                return core.slo.report()
        return self._shards[0].slo.report()


class _FanoutFaults:
    """Fault-plane facade: scripted faults apply to every shard's
    supervisor (trace_replay's chaos coupling drives this)."""

    def __init__(self, shards: List[CoreScheduler]):
        self._shards = shards

    def __getattr__(self, name):
        def fan(*a, **kw):
            out = None
            for core in self._shards:
                out = getattr(core.supervisor.faults, name)(*a, **kw)
            return out
        return fan


class _ShardSupervisor:
    """Supervisor facade for fleet-level readers (degraded_paths union,
    shared fault plane); per-shard supervisors stay authoritative."""

    def __init__(self, shards: List[CoreScheduler]):
        self._shards = shards
        self.faults = _FanoutFaults(shards)

    @property
    def cycle_id(self) -> int:
        # fleet-level attach points (the AOT runtime's compile spans) read
        # one committing cycle id; the primary's is representative
        return self._shards[0].supervisor.cycle_id

    def degraded_paths(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for k, core in enumerate(self._shards):
            for path, tier in core.supervisor.degraded_paths().items():
                out[f"s{k}/{path}"] = tier
        return out

    def snapshot(self) -> dict:
        return {f"s{k}/{p}": s
                for k, core in enumerate(self._shards)
                for p, s in core.supervisor.snapshot().items()}


# ---------------------------------------------------------------------------
# The front end
# ---------------------------------------------------------------------------
class ShardedCoreScheduler(SchedulerAPI):
    """SchedulerAPI front end over N pipelined CoreScheduler shards.

    Routing: nodes to shards by ICI domain (ShardTopologyPartitioner),
    apps/asks to a stable home shard (crc32 of the application id — the
    whole gang solves in one shard, preserving gang contiguity), pinned
    asks to the shard owning their preferred node, releases broadcast
    (only the holder acts). All shards share one SchedulerCache (node reads
    scoped per shard by ShardCacheView), one MetricsRegistry (fleet-total
    counters; per-shard series carry a shard label), and one
    GlobalQuotaLedger."""

    def __init__(self, cache, n_shards: int, interval: float = 0.1,
                 solver_policy: Optional[str] = None,
                 solver_options=None, trace_spans: int = 4096,
                 supervisor_options=None, slo_options=None,
                 epoch_seconds: float = 0.0, aot_namespace: bool = False,
                 failover_options=None, journey_capacity: int = 8192,
                 flightrec_options=None, delivery_high_water: int = 1024,
                 usage_mirror: bool = True, ledger_endpoint: str = "",
                 ledger_serve: bool = False, ledger_client_options=None,
                 host_id: str = ""):
        # aot_namespace=True gives each shard its own executable namespace
        # in the AOT store (corruption/variant isolation for multi-process
        # deployments) at the cost of N compiles per program AND of the
        # bucket prewarm: warm_bucket runs outside any namespace, so
        # namespaced shards would miss every prewarmed entry. Default off:
        # in-process shards share executables — same program, same avals.
        if n_shards < 2:
            raise ValueError("ShardedCoreScheduler needs >= 2 shards; "
                             "use make_core_scheduler for the 1-shard case")
        self.cache = cache
        self.n = n_shards
        self._interval = interval
        self.obs = MetricsRegistry()
        self.ledger = GlobalQuotaLedger(registry=self.obs)
        # -- ledger as a service (round 22) -----------------------------------
        # with ledger_serve: the in-process GlobalQuotaLedger stays the
        # commit-time authority but moves BEHIND a LedgerServer socket, and
        # every shard couples through a LedgerClient (deadlines, breaker,
        # degraded mode, idempotent replay) — the RPC boundary that lets a
        # shard live in another process. With a bare ledger_endpoint the
        # authority lives in ANOTHER process and only the client is built.
        # Default (neither): self.ledger stays the direct object, byte-
        # identical to rounds 16-21 (pinned by test).
        self.ledger_authority = self.ledger
        self.ledger_server = None
        self.lease_monitor = None
        self.host_id = host_id or f"host-{os.getpid()}"
        self._ledger_rpc = bool(ledger_serve or ledger_endpoint)
        if self._ledger_rpc:
            from yunikorn_tpu.core.ledger_service import (
                LedgerClient, LedgerClientOptions, LedgerServer)
            copts = ledger_client_options or LedgerClientOptions()
            if ledger_serve:
                self.ledger_server = LedgerServer(self.ledger_authority)
                srv_host, srv_port = self.ledger_server.start()
                endpoint = ledger_endpoint or f"{srv_host}:{srv_port}"
            else:
                self.ledger_authority = None  # lives in another process
                endpoint = ledger_endpoint
            self.ledger = LedgerClient(endpoint, copts, registry=self.obs,
                                       client_id=self.host_id)
        self.fanout = ShardCacheFanout(cache, n_shards)
        self.partitioner = ShardTopologyPartitioner(n_shards, seed=0)
        self.epoch_seconds = float(epoch_seconds)
        self.epoch = 0
        self.callback = None
        self.rm_id = ""
        self._rm_request = None
        # routing state (under _mu; _mu is ALWAYS taken before shard locks,
        # and never while holding one)
        self._mu = threading.RLock()
        self._app_home: Dict[str, int] = {}
        self._app_shards: Dict[str, Set[int]] = {}
        self._app_reqs: Dict[str, object] = {}
        self._ask_home: Dict[str, int] = {}
        self._asks: Dict[str, object] = {}
        self._node_reg: Dict[str, SiNodeInfo] = {}
        self._node_sched: Dict[str, bool] = {}
        # repair + stats state (under _stats_mu; leaf-level only — safe to
        # take while a shard lock is held, never held across shard calls)
        self._stats_mu = threading.Lock()
        self._repair: Dict[str, dict] = {}
        # asks holding a live victim credit on the ledger (posted by the
        # exhausted-repair branch; cleared on placement/forget)
        self._credited: Set[str] = set()
        self._repair_allocs: Dict[str, Set[str]] = {}   # app -> repaired keys
        # allocation key -> (committing shard, app id); the app id makes
        # app-removal purge possible (removal emits no per-key releases)
        self._alloc_shard: Dict[str, Tuple[int, str]] = {}
        # allocation key -> live Allocation object (commits, restores,
        # recovery registrations). The failover re-home replays these into
        # the app's new home shard — the quarantined shard's own state is
        # unreachable (its locks may be held forever by the wedged cycle),
        # so the front keeps the authoritative copy.
        self._allocs: Dict[str, object] = {}
        # apps whose Completed update was suppressed while repaired
        # allocations lived in other shards: re-emitted by
        # _note_allocations when the last such allocation releases
        self._suppressed_apps: Set[str] = set()
        self._bound_per_shard = [0] * n_shards
        self._repair_placed = 0
        self._suppressed_completions = 0
        self._epoch_thread: Optional[threading.Thread] = None
        self._epoch_stop = threading.Event()
        m = self.obs
        m.gauge("shard_count",
                "control-plane shards in this scheduler process"
                ).set(n_shards)
        self._m_asks = m.counter(
            "shard_asks_total", "asks routed to each shard",
            labelnames=("shard",))
        self._m_bound = m.counter(
            "shard_bound_total", "allocations committed by each shard",
            labelnames=("shard",))
        self._m_repair = m.counter(
            "shard_repair_total",
            "stranded-ask repair outcomes (migrated = ask moved to an "
            "untried shard, placed = a repaired ask committed, exhausted = "
            "every shard tried and the ask is genuinely unschedulable)",
            labelnames=("outcome",))
        self._m_node_migrations = m.counter(
            "shard_node_migrations_total",
            "nodes moved between shards by epoch re-seeding")
        self._m_epochs = m.counter(
            "shard_epoch_total", "shard-partition re-seed epochs completed")
        # -- async front end (round 20) --------------------------------------
        self._m_qdepth = m.gauge(
            "shard_queue_depth",
            "pending deliveries in each shard's async delivery queue "
            "(inflight delivery counts as 1)", labelnames=("shard",))
        self._m_ack = m.histogram(
            "shard_delivery_ack_ms",
            "enqueue-to-ack latency of async shard deliveries — the time a "
            "front-end call's payload waits before its shard's pump thread "
            "finishes applying it", labelnames=("shard",),
            buckets=MS_BUCKETS)
        self._m_shed = m.counter(
            "shard_queue_shed_total",
            "asks shed AWAY from a shard whose delivery queue passed its "
            "high-water mark (re-routed to the least-loaded active shard — "
            "the backpressure path; the ask is never dropped)",
            labelnames=("shard",))
        self._m_mirror_div = m.gauge(
            "shard_ledger_mirror_divergence",
            "cells where the device-resident usage mirror differs from the "
            "GlobalQuotaLedger's confirmed usage after a drain — commit-time "
            "authority exactness is gated on this pinning at 0")
        # -- the shards -------------------------------------------------------
        # build kwargs retained: shard failover REBUILDS a quarantined
        # shard's core from scratch at rejoin (the in-process analog of a
        # crashed scheduler process restarting)
        self._solver_policy = solver_policy
        self._solver_options = solver_options
        self._supervisor_options = supervisor_options
        self._slo_options = slo_options
        self._trace_spans = trace_spans
        self._aot_namespace = aot_namespace
        self._last_config: Optional[Tuple[str, object]] = None
        self._quarantined: Set[int] = set()
        self._rehomed_nodes_total = 0
        self._failover_last: Optional[dict] = None
        # -- fleet observability (round 20) ----------------------------------
        # ONE journey ledger and ONE flight recorder fleet-wide, built
        # BEFORE the shards so every core shares them (the front owns the
        # metrics families); the FleetTracer merges each shard's cycle
        # tracer with the front end's own routing/repair/ledger/failover
        # spans into one Chrome trace — one pid per shard plus pid 1 for
        # the front lane.
        self.journey = JourneyLedger(capacity=journey_capacity, registry=m)
        self.flightrec = FlightRecorder(
            flightrec_options or FlightRecorderOptions(), registry=m)
        self.tracer = FleetTracer()
        # device-resident usage mirror (round 20): the ledger stays the
        # commit-time authority; the mirror carries confirmed usage on
        # device, pre-reduced across shards, so each shard's gate precheck
        # reads fleet usage with zero lock acquisitions. Built BEFORE the
        # shards so every core shares it.
        self.usage_mirror = None
        if usage_mirror:
            from yunikorn_tpu.ops.ledger_mirror import DeviceUsageMirror
            self.usage_mirror = DeviceUsageMirror(
                n_shards, divergence_gauge=self._m_mirror_div)
            self.ledger.attach_mirror(self.usage_mirror)
        self.shards: List[CoreScheduler] = []
        self._callbacks: List[Optional[_ShardCallback]] = [None] * n_shards
        for k in range(n_shards):
            self.shards.append(self._build_shard(k))
        # async delivery queues (round 20): one pump per shard owns every
        # front-end call into that core; front-end update_* enqueue+return
        self._delivery_high_water = int(delivery_high_water)
        self.delivery: List[ShardDeliveryQueue] = [
            ShardDeliveryQueue(
                k, self.shards[k], high_water=self._delivery_high_water,
                ack_observe=self._on_delivery_ack,
                depth_set=self._on_delivery_depth)
            for k in range(n_shards)]
        # stable zeros from boot: dashboards (and obs_smoke) read these
        # families before any delivery, shed, or mirror drain has happened
        for k in range(n_shards):
            self._m_qdepth.set(0, shard=str(k))
            self._m_shed.inc(0, shard=str(k))
        self._m_mirror_div.set(0)
        self._register_flightrec_sources()
        self.slo = _ShardSlo(self.shards, front=self)
        self.supervisor = _ShardSupervisor(self.shards)
        from yunikorn_tpu.robustness.failover import (FailoverOptions,
                                                      ShardSupervisor,
                                                      failover_source)
        from yunikorn_tpu.robustness.health import HealthMonitor

        self.failover = ShardSupervisor(
            n_shards, failover_options or FailoverOptions(),
            self.quarantine_shard, self.rejoin_shard, registry=self.obs)
        self.failover.set_cores(lambda: list(self.shards))
        self.health = HealthMonitor()
        self.health.register("shards", self._shards_health)
        self.health.register("failover", failover_source(self.failover))
        if self._ledger_rpc:
            # the client records a ledger_degraded post-mortem trigger on
            # every transition into degraded/fail_closed
            self.ledger.attach_flightrec(self.flightrec)
            # cross-host failover (ROADMAP (e)): the ledger's lease table
            # is the liveness authority — this host heartbeats over the
            # ledger connection, and a peer host whose lease expires gets
            # its shards quarantined/re-homed by OUR supervisor through
            # the round-18 machinery
            from yunikorn_tpu.robustness.failover import HostLeaseMonitor
            self.lease_monitor = HostLeaseMonitor(
                self.ledger, self.host_id, list(range(n_shards)),
                self._lease_quarantine,
                ttl_s=getattr(self.ledger.options, "lease_ttl_s", 15.0),
                registry=self.obs)

    def _lease_quarantine(self, idx: int, reason: str) -> bool:
        """Lease-expiry quarantines run the identical re-home transaction
        as the in-process failure-domain supervisor, then record the
        transition on it — states, counters and the rejoin ladder must
        reflect cross-host detections too."""
        t0 = time.time()
        ok = self.quarantine_shard(idx, reason)
        if ok:
            self.failover.note_quarantined(idx, reason, time.time() - t0)
        return ok

    def _build_shard(self, k: int) -> CoreScheduler:
        view = ShardCacheView(self.fanout, k)
        so = (dataclasses.replace(self._solver_options)
              if self._solver_options is not None else None)
        sup = (dataclasses.replace(self._supervisor_options)
               if self._supervisor_options is not None else None)
        slo = (dataclasses.replace(self._slo_options)
               if self._slo_options is not None else None)
        core = CoreScheduler(
            view, interval=self._interval, solver_policy=self._solver_policy,
            solver_options=so, trace_spans=self._trace_spans,
            supervisor_options=sup, slo_options=slo, registry=self.obs,
            shard_label=str(k), quota_ledger=self.ledger,
            aot_namespace=(f"shard{k}" if self._aot_namespace else None),
            journey=self.journey, flightrec=self.flightrec)
        core.shard_index = k
        core.usage_mirror = self.usage_mirror
        # mirror journal epoch stamp: a quarantined zombie's stale stamp
        # fences its late refreshes out of the fold (rebuilt cores get the
        # post-fence epoch here)
        core._mirror_epoch = (self.usage_mirror.epoch_of(k)
                              if self.usage_mirror is not None else 0)
        self.tracer.register(k, core.tracer, name=f"shard {k}")
        return core

    # ------------------------------------------------------- compat surface
    @property
    def primary(self):
        # shard 0 can be REBUILT by failover rejoin: always read the list
        return self.shards[0]

    @property
    def partition(self):
        return self.primary.partition

    @property
    def partitions(self):
        return self.primary.partitions

    @property
    def queues(self):
        return self.primary.queues

    @property
    def queue_trees(self):
        return self.primary.queue_trees

    @property
    def encoder(self):
        return self.primary.encoder

    @property
    def _lock(self):
        return self.primary._lock

    @property
    def _first_cycle_ms(self) -> Optional[float]:
        vals = [c._first_cycle_ms for c in self.shards
                if c._first_cycle_ms is not None]
        return min(vals) if vals else None

    @property
    def metrics(self) -> dict:
        return self.metrics_snapshot()

    def metrics_snapshot(self) -> dict:
        snap = self.obs.snapshot()
        last = {}
        for k, core in enumerate(self.shards):
            if k in self._quarantined:
                continue  # a wedged zombie may hold its core lock forever
            with core._lock:
                for pname, entry in core._last_cycle.items():
                    last[f"shard{k}/{pname}"] = dict(entry)
        if last:
            snap["last_cycle"] = last
        return snap

    def health_report(self) -> dict:
        return self.health.report()

    def _shards_health(self) -> dict:
        per = {}
        healthy = True
        live = True
        for k, core in enumerate(self.shards):
            if k in self._quarantined:
                # a quarantined shard is a KNOWN failure domain being
                # handled: the failover source reports it; it must not
                # read as fleet liveness loss (the survivors serve)
                per[f"s{k}"] = {"state": "quarantined"}
                healthy = False
                continue
            rep = core.health.report()
            per[f"s{k}"] = {"ready": rep["ready"], "live": rep["live"]}
            healthy = healthy and rep["ready"]
            live = live and rep["live"]
        out = {"healthy": healthy, "shards": per,
               "ledger": self.ledger.stats()}
        if not live:
            out["live"] = False
        return out

    def recent_preemptions(self) -> List[dict]:
        out = []
        for k, core in enumerate(self.shards):
            if k in self._quarantined:
                continue
            out.extend(core.recent_preemptions())
        out.sort(key=lambda p: p.get("at", 0))
        return out

    def validate_configuration(self, config_text: str):
        return self.primary.validate_configuration(config_text)

    def get_partition_dao(self) -> dict:
        dao = self.primary.get_partition_dao()
        dao["shards"] = self.shard_report()
        return dao

    def state_dump(self) -> str:
        import json

        return json.dumps(self.get_partition_dao(), default=str)

    def observe_pod_bound(self, allocation_key: str) -> None:
        for core in self.shards:
            core.observe_pod_bound(allocation_key)

    def fleet_fragmentation(self) -> float:
        """ICI-domain fragmentation across every shard's free capacity.
        Domains never straddle shards, so the global measure composes from
        per-shard (max, total) free-unit aggregates exactly."""
        import numpy as np

        from yunikorn_tpu.topology.model import domain_free_units

        best = 0
        total = 0
        for k, core in enumerate(self.shards):
            if k in self._quarantined:
                # its nodes already re-homed; the zombie encoder's stale
                # rows would double-count the migrated capacity
                continue
            na = core.encoder.nodes
            n_dom = na.num_ici_domains
            if n_dom <= 0:
                continue
            free_i = np.floor(na.free).astype(np.int64)
            cap_i = np.floor(na.capacity_arr).astype(np.int64)
            free_d, _ = domain_free_units(na.topo[:, 2], free_i, cap_i,
                                          n_dom)
            if free_d.size:
                best = max(best, int(free_d.max()))
                total += int(free_d.sum())
        if total <= 0:
            return 0.0
        return round(1.0 - best / total, 6)

    def shard_report(self) -> dict:
        """Operator surface (/ws/v1/shards + the replay fingerprint):
        per-shard routing/commit counts, repair + ledger + epoch state."""
        with self._stats_mu:
            bound = list(self._bound_per_shard)
            repair_live = len(self._repair)
            repair_placed = self._repair_placed
            suppressed = self._suppressed_completions
        states = self.failover.states()
        shards = []
        for k, core in enumerate(self.shards):
            shards.append({
                "shard": k,
                "state": states.get(k, "serving"),
                "nodes": len(self.fanout.names_for(k)),
                "bound": bound[k],
                # _cycle_seq is per-core (the registry's solve_count counter
                # is shared across shards, i.e. fleet-total)
                "cycles": int(core._cycle_seq),
                "degraded": core.supervisor.degraded_paths(),
                "delivery": self.delivery[k].stats(),
            })
        fo = self.failover.report()
        with self._mu:
            fo["rehomed_nodes_total"] = self._rehomed_nodes_total
            if self._failover_last is not None:
                fo["last_rehome"] = dict(self._failover_last)
        return {
            "count": self.n,
            "epoch": self.epoch,
            "epoch_seconds": self.epoch_seconds,
            "node_migrations": int(self._m_node_migrations.value()),
            "shards": shards,
            "repair": {
                "in_flight": repair_live,
                "placed": repair_placed,
                "migrated": int(self._m_repair.value(outcome="migrated")),
                "exhausted": int(self._m_repair.value(outcome="exhausted")),
            },
            "ledger": self.ledger.stats(),
            "mirror": (self.usage_mirror.stats()
                       if self.usage_mirror is not None else None),
            "suppressed_completions": suppressed,
            "failover": fo,
        }

    def _register_flightrec_sources(self) -> None:
        """Fleet-level bundle sources. Every source reads leaf-locked or
        front-owned state only — never a shard's core lock, which on the
        quarantine trigger may be held forever by the wedged cycle."""
        fr = self.flightrec
        fr.add_source(
            "trace",
            lambda: self.tracer.chrome_trace(window_s=fr.options.window_s))
        fr.add_source("metrics", lambda: self.obs.snapshot())
        fr.add_source("journeys",
                      lambda: self.journey.tail(fr.options.journey_tail))
        fr.add_source("ledger_audit", lambda: {
            "violations": self.ledger.audit(),
            "stats": self.ledger.stats()})
        fr.add_source("cycles", lambda: {
            f"s{k}": list(core._cycle_log)
            for k, core in enumerate(self.shards)})
        fr.add_source("duel", lambda: {
            f"s{k}": {"last_solve": dict(core._last_solve_stats),
                      "last_pack": dict(core._last_pack_stats)}
            for k, core in enumerate(self.shards)})
        # NOT shard_report: it takes the front _mu, and a trigger can fire
        # on a shard cycle thread while a quarantine transaction holds _mu
        # and is delivering into that same shard (classic ABBA)
        fr.add_source("shards", lambda: {
            "count": self.n,
            "epoch": self.epoch,
            "states": self.failover.states(),
            "failover": self.failover.report(),
        })

    # ------------------------------------------------------- async delivery
    def _on_delivery_ack(self, idx: int, dt_s: float) -> None:
        self._m_ack.observe(dt_s * 1000.0, shard=str(idx))

    def _on_delivery_depth(self, idx: int, depth: int) -> None:
        self._m_qdepth.set(depth, shard=str(idx))

    def _deliver(self, shard: int, method: str, *args) -> bool:
        """Enqueue one delivery for `shard`'s pump thread. Safe under _mu
        (leaf lock only — never calls into a core). A False return means
        the queue is fenced (shard quarantined between routing and
        delivery); the quarantine transaction re-derives everything from
        the front's routing state, so the drop is safe."""
        return self.delivery[shard].enqueue(method, *args)

    def flush(self, timeout: float = 10.0) -> bool:
        """Drain every live delivery queue (test/bench barrier; production
        never waits). Fenced/wedged queues are skipped — a wedged shard
        must bound this call, not extend it."""
        deadline = time.time() + max(0.0, timeout)
        ok = True
        for k, q in enumerate(self.delivery):
            if k in self._quarantined or q.dead:
                continue
            ok = q.flush(timeout=max(0.0, deadline - time.time())) and ok
        return ok

    # ---------------------------------------------------------- SchedulerAPI
    def register_resource_manager(self, request, callback) -> None:
        self.callback = callback
        self.rm_id = request.rm_id
        self._rm_request = request
        for k, core in enumerate(self.shards):
            cb = _ShardCallback(self, k, callback)
            self._callbacks[k] = cb
            core.register_resource_manager(request, cb)

    def update_configuration(self, config: str, extra_config) -> None:
        with self._mu:
            # retained so a failover-rebuilt shard replays the live config
            self._last_config = (config, extra_config)
            quarantined = set(self._quarantined)
        for k in range(self.n):
            if k not in quarantined:
                self._deliver(k, "update_configuration", config,
                              extra_config)

    def update_node(self, request: NodeRequest) -> None:
        # routed per shard under ONE _mu pass, delivered as one batched
        # NodeRequest per shard (a 10k-node fleet registration is N shard
        # calls, not 10k lock/callback/trigger round-trips)
        routed: Dict[int, List[SiNodeInfo]] = {}
        with self._mu:
            for info in request.nodes:
                if info.action in (NodeAction.CREATE,
                                   NodeAction.CREATE_DRAIN):
                    labels = self._node_labels(info)
                    old = self.fanout.owner_of(info.node_id)
                    shard = self.partitioner.assign(info.node_id, labels)
                    self.fanout.set_owner(info.node_id, shard)
                    self._node_reg[info.node_id] = dataclasses.replace(
                        info, existing_allocations=[])
                    self._node_sched[info.node_id] = (
                        info.action == NodeAction.CREATE)
                    if (old is not None and old != shard
                            and old not in self._quarantined):
                        # re-registration moved ownership (changed
                        # topology labels): decommission the old shard or
                        # it keeps the node registered forever (the same
                        # DECOMISSION+CREATE contract reseed_epoch uses).
                        # A quarantined old owner is unreachable (and its
                        # rebuilt replacement starts empty) — skip it.
                        routed.setdefault(old, []).append(SiNodeInfo(
                            node_id=info.node_id,
                            action=NodeAction.DECOMISSION))
                    routed.setdefault(shard, []).append(info)
                    continue
                shard = self.fanout.owner_of(info.node_id)
                if info.action == NodeAction.DECOMISSION:
                    self.partitioner.remove(info.node_id)
                    self.fanout.set_owner(info.node_id, None)
                    self._node_reg.pop(info.node_id, None)
                    self._node_sched.pop(info.node_id, None)
                elif info.action == NodeAction.DRAIN_NODE:
                    self._node_sched[info.node_id] = False
                elif info.action == NodeAction.DRAIN_TO_SCHEDULABLE:
                    self._node_sched[info.node_id] = True
                if shard is not None and shard not in self._quarantined:
                    routed.setdefault(shard, []).append(info)
        for shard, infos in routed.items():
            self._deliver(shard, "update_node", NodeRequest(nodes=infos))

    def _node_labels(self, info: SiNodeInfo) -> Optional[Dict[str, str]]:
        node = getattr(info, "node", None)
        labels = getattr(getattr(node, "metadata", None), "labels", None)
        if labels:
            return labels
        cached = self.cache.get_node(info.node_id)
        if cached is not None:
            return getattr(cached.node.metadata, "labels", None)
        return None

    def _first_active_from(self, base: int) -> int:
        """First non-quarantined shard at or after `base` (wrapping) —
        THE shard-walk rule, shared by home assignment, failover
        re-homing and unknown-owner fallbacks so the policy cannot drift
        between them; `base` itself when nothing is active (degenerate,
        guarded elsewhere by never-quarantine-the-last-shard)."""
        for off in range(self.n):
            k = (base + off) % self.n
            if k not in self._quarantined:
                return k
        return base

    def _home_shard(self, app_id: str) -> int:
        shard = self._app_home.get(app_id)
        if shard is not None and shard not in self._quarantined:
            return shard
        # crc32 walked forward to the first non-quarantined shard: the
        # fault-free fleet keeps the exact pre-failover assignment (offset
        # 0 always wins), a degraded fleet re-homes deterministically
        shard = self._first_active_from(zlib.crc32(app_id.encode()) % self.n)
        self._app_home[app_id] = shard
        return shard

    def update_application(self, request: ApplicationRequest) -> None:
        routed: Dict[int, ApplicationRequest] = {}
        with self._mu:
            for add in request.new:
                shard = self._home_shard(add.application_id)
                self._app_reqs[add.application_id] = add
                self._app_shards.setdefault(add.application_id,
                                            set()).add(shard)
                routed.setdefault(
                    shard, ApplicationRequest()).new.append(add)
            for rem in request.remove:
                shards = self._app_shards.pop(rem.application_id,
                                              None) or set(range(self.n))
                self._app_home.pop(rem.application_id, None)
                self._app_reqs.pop(rem.application_id, None)
                # purge the removed app's routing entries: the core emits
                # no per-key releases on app removal, so these would
                # otherwise leak (and misroute a reused key's release)
                dead = [k for k, a in self._asks.items()
                        if a.application_id == rem.application_id]
                for k in dead:
                    self._asks.pop(k, None)
                    self._ask_home.pop(k, None)
                with self._stats_mu:
                    self._repair_allocs.pop(rem.application_id, None)
                    self._suppressed_apps.discard(rem.application_id)
                    for k in dead:
                        self._repair.pop(k, None)
                    for k in [k for k, v in self._alloc_shard.items()
                              if v[1] == rem.application_id]:
                        self._alloc_shard.pop(k, None)
                        self._allocs.pop(k, None)
                for shard in shards:
                    if shard not in self._quarantined:
                        routed.setdefault(
                            shard, ApplicationRequest()).remove.append(rem)
        for shard, req in routed.items():
            self._deliver(shard, "update_application", req)

    def update_allocation(self, request: AllocationRequest) -> None:
        t_route0 = time.time()
        routed: Dict[int, AllocationRequest] = {}
        guest_apps: Dict[int, ApplicationRequest] = {}
        with self._mu:
            for ask in request.asks:
                shard = None
                if ask.preferred_node:
                    shard = self.fanout.owner_of(ask.preferred_node)
                if shard is None:
                    shard = self._home_shard(ask.application_id)
                    # backpressure: when the home queue is past its
                    # high-water mark, shed this UNPINNED ask to the
                    # least-loaded active shard (the shed-to-repair path —
                    # the ask re-enters scheduling there as a repair
                    # guest, never dropped) instead of deepening a
                    # possibly-wedged backlog. Pinned asks must reach the
                    # node's owner; non-ask traffic is never shed.
                    if self.delivery[shard].over_high_water():
                        alts = [k for k in range(self.n)
                                if k != shard
                                and k not in self._quarantined
                                and not self.delivery[k].dead]
                        if alts:
                            tgt = min(alts,
                                      key=lambda k: self.delivery[k].depth())
                            if (self.delivery[tgt].depth()
                                    < self.delivery[shard].depth()):
                                self._m_shed.inc(shard=str(shard))
                                self._m_repair.inc(outcome="shed")
                                shard = tgt
                if shard != self._home_shard(ask.application_id):
                    self._ensure_guest_app_locked(ask.application_id,
                                                  shard, guest_apps)
                self._ask_home[ask.allocation_key] = shard
                self._asks[ask.allocation_key] = ask
                routed.setdefault(
                    shard, AllocationRequest()).asks.append(ask)
                self._m_asks.inc(shard=str(shard))
                with self._stats_mu:
                    # fresh work revokes a pending fleet-level Completed
                    # re-emit (the app is visibly not done anymore)
                    self._suppressed_apps.discard(ask.application_id)
            for alloc in request.allocations:
                if alloc.foreign:
                    shard = self.fanout.owner_of(alloc.node_id)
                    if shard is None or shard in self._quarantined:
                        # unknown/unreachable owner: first active shard (a
                        # quarantined shard must never receive deliveries —
                        # its wedged lock could block this caller forever)
                        shard = self._first_active_from(0)
                else:
                    shard = self._home_shard(alloc.application_id)
                    with self._stats_mu:
                        # recovery/restore registration: track the object
                        # (and its holder) so a later failover can replay
                        # it into a surviving shard
                        self._allocs[alloc.allocation_key] = alloc
                        self._alloc_shard[alloc.allocation_key] = (
                            shard, alloc.application_id)
                routed.setdefault(
                    shard, AllocationRequest()).allocations.append(alloc)
            for rel in request.releases:
                # route each release to the shard(s) known to hold the key
                # (pending ask home + committing shard); unknown keys —
                # foreign allocations, recovery residue — broadcast. A 50k
                # mass release then costs 50k walks, not 50k x N.
                self._asks.pop(rel.allocation_key, None)
                home = self._ask_home.pop(rel.allocation_key, None)
                with self._stats_mu:
                    self._repair.pop(rel.allocation_key, None)
                    keys = self._repair_allocs.get(rel.application_id)
                    if keys is not None:
                        keys.discard(rel.allocation_key)
                    held = self._alloc_shard.get(rel.allocation_key)
                    held = held[0] if held is not None else None
                # quarantined shards are unreachable — their keys were
                # re-attributed at quarantine, so the surviving holder (or
                # the broadcast) performs the release + ledger drop
                targets = {s for s in (home, held)
                           if s is not None and s not in self._quarantined}
                if not targets:
                    targets = set(range(self.n)) - self._quarantined
                for shard in targets:
                    routed.setdefault(
                        shard, AllocationRequest()).releases.append(rel)
        # guest registrations must land BEFORE the asks that need them:
        # both ride the same per-shard FIFO, so enqueue order is delivery
        # order
        for shard, req in guest_apps.items():
            self._deliver(shard, "update_application", req)
        for shard, req in routed.items():
            self._deliver(shard, "update_allocation", req)
        if request.asks or request.releases:
            # front-lane span: the routing + delivery hop every ask pays
            # before any shard's gate sees it
            self.tracer.add("route", 0, t_route0, time.time(),
                            asks=len(request.asks),
                            releases=len(request.releases),
                            shards=len(routed))

    def _ensure_guest_app_locked(self, app_id: str, shard: int,
                                 routed: Optional[
                                     Dict[int, ApplicationRequest]]
                                 ) -> bool:
        """Register the app in `shard` as a repair guest if absent (front
        _mu held). `routed` must be an ApplicationRequest-keyed map (the
        caller delivers it BEFORE any asks that depend on the guest);
        None enqueues the registration immediately — the shard's FIFO
        keeps it ahead of any ask the caller enqueues afterwards."""
        shards = self._app_shards.setdefault(app_id, set())
        if shard in shards:
            return False
        add = self._app_reqs.get(app_id)
        if add is None:
            return False
        guest = dataclasses.replace(add, tags=dict(add.tags))
        guest.tags[GUEST_APP_TAG] = "true"
        shards.add(shard)
        if routed is not None:
            routed.setdefault(shard, ApplicationRequest()).new.append(guest)
        else:
            self._deliver(shard, "update_application",
                          ApplicationRequest(new=[guest]))
        return True

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        for k, core in enumerate(self.shards):
            core.start()
            # phase-stagger the cycle loops: shard k's device solve then
            # overlaps its neighbors' host-side gate/commit windows
            if k + 1 < self.n:
                time.sleep(self._interval * (k + 1) / self.n / 4)
        if self.epoch_seconds > 0 and self._epoch_thread is None:
            self._epoch_stop.clear()
            self._epoch_thread = threading.Thread(
                target=self._epoch_loop, name="shard-epoch", daemon=True)
            self._epoch_thread.start()
        self.failover.start()
        if self.lease_monitor is not None:
            self.lease_monitor.start()

    def stop(self) -> None:
        if self.lease_monitor is not None:
            self.lease_monitor.stop()
        self.failover.stop()
        self._epoch_stop.set()
        if self._epoch_thread is not None:
            self._epoch_thread.join(timeout=5)
            self._epoch_thread = None
        # bounded: let in-flight deliveries land, then stop the pumps (a
        # wedged queue is skipped by flush and its pump is epoch-fenced)
        self.flush(timeout=5.0)
        for q in self.delivery:
            q.stop()
        for k, core in enumerate(self.shards):
            if k in self._quarantined:
                # a quarantined core may be WEDGED with its pipeline mutex
                # held forever — a full stop() would join/drain into that
                # lock and hang shutdown; the soft-stop flag was already
                # cleared at quarantine, so just leave the zombie behind
                # (daemon threads; the process owns cleanup)
                core._running.clear()
                continue
            core.stop()
        if self._ledger_rpc:
            self.ledger.close()
        if self.ledger_server is not None:
            self.ledger_server.stop()

    def trigger(self) -> None:
        for k, core in enumerate(self.shards):
            if k not in self._quarantined:
                core.trigger()

    def schedule_once(self) -> int:
        """Drive one cycle on every serving shard (test/bench surface;
        production runs the shards' own staggered loops). Flushes the
        async delivery queues first so a just-submitted ask is visible to
        the cycle it drives — the synchronous semantics direct drivers
        have always had."""
        self.flush(timeout=10.0)
        total = 0
        for k, core in enumerate(self.shards):
            if k not in self._quarantined:
                total += core.schedule_once()
        return total

    # ------------------------------------------------------ epoch re-seeding
    def _epoch_loop(self) -> None:
        while not self._epoch_stop.wait(self.epoch_seconds):
            try:
                self.reseed_epoch()
            except Exception:
                logger.exception("shard epoch re-seed failed; assignment "
                                 "unchanged this epoch")

    def reseed_epoch(self) -> int:
        """Advance the partition epoch: re-assign domains under a fresh
        seed and migrate every moved node (DECOMISSION from the old shard,
        CREATE into the new one, drain state preserved). Returns the
        number of nodes migrated."""
        with self._mu:
            self.epoch += 1
            moves = self.partitioner.reseed(self.epoch)
            plan = []
            for name, (old, new) in sorted(moves.items()):
                reg = self._node_reg.get(name)
                if reg is None:
                    continue
                self.fanout.set_owner(name, new)
                plan.append((name, old, new, reg,
                             self._node_sched.get(name, True)))
        for name, old, new, reg, schedulable in plan:
            if old not in self._quarantined:
                self._deliver(old, "update_node", NodeRequest(nodes=[
                    SiNodeInfo(node_id=name,
                               action=NodeAction.DECOMISSION)]))
            create = dataclasses.replace(
                reg,
                action=(NodeAction.CREATE if schedulable
                        else NodeAction.CREATE_DRAIN),
                existing_allocations=[])
            self._deliver(new, "update_node", NodeRequest(nodes=[create]))
        if plan:
            self._m_node_migrations.inc(len(plan))
            logger.info("shard epoch %d: migrated %d nodes", self.epoch,
                        len(plan))
        self._m_epochs.inc()
        return len(plan)

    # --------------------------------------------------- failure domains
    def quarantine_shard(self, idx: int, reason: str = "manual") -> bool:
        """Quarantine one dead/wedged shard: stop routing to it, re-home
        its whole ICI domains onto surviving shards, reconcile the ledger
        (its pending reservations released, confirmed usage re-attributed
        to each app's new home — audit() stays zero-violation throughout),
        re-register its apps on survivors and re-admit its parked asks.

        Runs entirely under the front _mu, delivers only via the async
        queues (never a direct core call — _mu is held only for routing
        state), and NEVER touches the quarantined core: a wedged cycle
        may hold that core's lock and pipeline mutex forever. Bound pods
        stay bound — node occupancy lives in the shared cache and the
        ledger keeps their confirmed usage under the same keys."""
        done_apps: List[str] = []
        t_q0 = time.time()
        with self._mu:
            if idx in self._quarantined or idx < 0 or idx >= self.n:
                return False
            if self.n - len(self._quarantined) <= 1:
                return False  # never amputate the last serving shard
            self._quarantined.add(idx)
            self.partitioner.set_active(idx, False)
            old_core = self.shards[idx]
            cb = self._callbacks[idx]
            if cb is not None:
                cb.dead = True  # zombie emissions fenced from the fleet
            # fence the delivery queue: drop its undelivered backlog (the
            # front's routing state re-derives it below — parked asks
            # re-admit, node domains re-home from _node_reg) and epoch-
            # fence the pump so a later unwedge cannot deliver into the
            # zombie. Dropped RELEASES are the one class with no other
            # source of truth once the holder re-attributes — collect them
            # for a survivor re-broadcast in step 6.
            dropped = self.delivery[idx].fence()
            dropped_releases = [
                rel for method, args in dropped
                if method == "update_allocation"
                for rel in args[0].releases]
            # snapshot the dying shard's trace rings BEFORE the engine is
            # detached: the frozen lane keeps its final cycle spans
            # exportable, and the staged copy guarantees the quarantine
            # bundle written after this transaction still contains them
            # even if the zombie object is dropped by a later rejoin
            frozen = self.tracer.freeze(idx)
            if frozen is not None:
                self.flightrec.stage(
                    "dead_shard_trace",
                    frozen.chrome_trace(
                        pid=FRONT_PID + 1 + idx,
                        process_name=f"shard {idx} (quarantined)"))
            # fence the zombie off the ledger too: a cycle that unwedges
            # later must not force-charge keys the fleet re-admitted
            old_core.quota_ledger = None
            # and off the usage mirror, the same way the delivery fence
            # drops stale backlog: bump the shard's mirror epoch so a
            # zombie cycle's late refresh (its _mirror_epoch is now stale)
            # cannot scatter usage_apply rows into the fold — any deltas
            # it drained but never folded are requeued on the authority
            old_core.usage_mirror = None
            if self.usage_mirror is not None:
                self.usage_mirror.fence_shard(idx)
            old_core._running.clear()  # soft-stop; never join a wedged loop
            try:
                with old_core._wake:
                    old_core._wake.notify_all()
            except Exception:
                pass
            try:
                # the dead engine must stop consuming the shared e2e
                # stream and ticking at scrape time
                old_core.slo.detach_core(old_core)
            except Exception:
                logger.exception("slo detach failed for shard %d", idx)

            # -- 1. park the shard's pending asks; release reservations --
            with self._stats_mu:
                committed = set(self._alloc_shard)
            parked = [(key, ask) for key, ask in self._asks.items()
                      if self._ask_home.get(key) == idx
                      and key not in committed]
            for key, _ask in parked:
                self.ledger.release_reservation(key)

            # -- 2. re-home apps whose home shard died --
            app_moves: Dict[str, int] = {}
            for app_id, home in list(self._app_home.items()):
                if home != idx:
                    continue
                new = self._first_active_from(
                    zlib.crc32(app_id.encode()) % self.n)
                app_moves[app_id] = new
                self._app_home[app_id] = new
            for shards_of_app in self._app_shards.values():
                shards_of_app.discard(idx)
            reg: Dict[int, ApplicationRequest] = {}
            for app_id in sorted(app_moves):
                add = self._app_reqs.get(app_id)
                if add is None:
                    continue
                new = app_moves[app_id]
                member = self._app_shards.setdefault(app_id, set())
                rehomed = dataclasses.replace(add, tags=dict(add.tags))
                rehomed.tags.pop(GUEST_APP_TAG, None)
                rehomed.tags[SHARD_REHOME_APP_TAG] = "true"
                member.add(new)
                reg.setdefault(new, ApplicationRequest()).new.append(rehomed)

            # -- 3. re-attribute the shard's committed allocations --
            restores: Dict[int, List] = {}
            with self._stats_mu:
                for key, (holder, app_id) in list(self._alloc_shard.items()):
                    if holder != idx:
                        continue
                    target = self._app_home.get(app_id)
                    if target is None or target in self._quarantined:
                        continue  # unknown app: recovery residue, leave it
                    alloc = self._allocs.get(key)
                    if alloc is None:
                        continue
                    self._alloc_shard[key] = (target, app_id)
                    restores.setdefault(target, []).append(alloc)
                    # a repaired allocation landing at its app's home is
                    # no longer "repaired elsewhere"
                    keys = self._repair_allocs.get(app_id)
                    if keys is not None:
                        keys.discard(key)
                        if not keys:
                            self._repair_allocs.pop(app_id, None)
                            if app_id in self._suppressed_apps:
                                self._suppressed_apps.discard(app_id)
                                done_apps.append(app_id)

            # -- 4. re-home the shard's node domains (whole ICI domains,
            #       the reseed DECOMISSION->CREATE contract minus the
            #       DECOMISSION: the dead shard is unreachable) --
            moves = self.partitioner.evacuate(idx)
            node_creates: Dict[int, List[SiNodeInfo]] = {}
            for name in sorted(moves):
                _old, new = moves[name]
                self.fanout.set_owner(name, new)
                reg_info = self._node_reg.get(name)
                if reg_info is None:
                    continue
                create = dataclasses.replace(
                    reg_info,
                    action=(NodeAction.CREATE
                            if self._node_sched.get(name, True)
                            else NodeAction.CREATE_DRAIN),
                    existing_allocations=[])
                node_creates.setdefault(new, []).append(create)

            # -- 5. re-admit the parked asks at each app's (new) home --
            ask_routes: Dict[int, AllocationRequest] = {}
            for key, ask in parked:
                target = self._app_home.get(ask.application_id)
                if target is None or target in self._quarantined:
                    continue
                with self._stats_mu:
                    # the fleet changed shape: restart the repair pass
                    self._repair.pop(key, None)
                self._ask_home[key] = target
                if target not in self._app_shards.get(ask.application_id,
                                                      set()):
                    self._ensure_guest_app_locked(ask.application_id,
                                                  target, reg)
                ask_routes.setdefault(
                    target, AllocationRequest()).asks.append(ask)
                self._m_asks.inc(shard=str(target))

            # -- 6. deliver (enqueues only: _mu never crosses a core call;
            #       per-shard FIFO keeps registrations ahead of the state
            #       that depends on them) --
            for shard, req in reg.items():
                self._deliver(shard, "update_application", req)
            for shard, allocs in restores.items():
                self._deliver(shard, "update_allocation",
                              AllocationRequest(allocations=list(allocs)))
            for shard, infos in node_creates.items():
                self._deliver(shard, "update_node", NodeRequest(nodes=infos))
            for shard, req in ask_routes.items():
                self._deliver(shard, "update_allocation", req)
            if dropped_releases:
                # releases fenced out of the dead queue: broadcast to the
                # survivors (only the holder acts) so a release routed to
                # the dying shard in its final window is never lost
                for shard in range(self.n):
                    if shard not in self._quarantined:
                        self._deliver(
                            shard, "update_allocation",
                            AllocationRequest(
                                releases=list(dropped_releases)))

            self._rehomed_nodes_total += len(moves)
            t_q1 = time.time()
            self._failover_last = {
                "shard": idx,
                "reason": reason,
                "nodes": len(moves),
                "apps": len(app_moves),
                "allocations": sum(len(v) for v in restores.values()),
                "asks": sum(len(r.asks) for r in ask_routes.values()),
                "at": round(t_q1, 3),
            }
            # front-lane spans: the whole quarantine transaction, and the
            # domain re-homing inside it, on the failover lane
            self.tracer.add("quarantine", 0, t_q0, t_q1, shard=idx,
                            reason=reason, apps=len(app_moves),
                            asks=self._failover_last["asks"])
            if moves:
                self.tracer.add("rehome", 0, t_q0, t_q1, shard=idx,
                                nodes=len(moves))
        if done_apps and self.callback is not None:
            from yunikorn_tpu.common.si import (ApplicationResponse,
                                                UpdatedApplication)

            self.callback.update_application(ApplicationResponse(updated=[
                UpdatedApplication(application_id=a, state="Completed",
                                   message="application completed")
                for a in done_apps]))
        logger.warning(
            "shard %d QUARANTINED (%s): re-homed %d nodes, %d apps, "
            "re-admitted %d asks", idx, reason,
            self._failover_last["nodes"], self._failover_last["apps"],
            self._failover_last["asks"])
        # the step-6 re-homing went through the async queues: wait for
        # the survivors to absorb it so the quarantine stays a synchronous
        # transaction for its callers (supervisor, REST, tests)
        self.flush(timeout=10.0)
        # trigger AFTER the _mu release: bundle sources must never run
        # while the quarantine transaction holds the front lock
        self.flightrec.record("quarantine", reason=f"shard {idx}: {reason}")
        return True

    def rejoin_shard(self, idx: int) -> bool:
        """Re-admit a quarantined shard: REBUILD its core from scratch (a
        fresh CoreScheduler — the in-process analog of a crashed scheduler
        process restarting; the zombie object and its threads are dropped)
        and advance the partition epoch so node domains flow back. The
        failover supervisor flips it to serving once the rebuilt loop
        completes a cycle — the healthy probe."""
        with self._mu:
            if idx not in self._quarantined:
                return False
            core = self._build_shard(idx)
            self.shards[idx] = core
            if self._rm_request is not None and self.callback is not None:
                cb = _ShardCallback(self, idx, self.callback)
                self._callbacks[idx] = cb
                core.register_resource_manager(self._rm_request, cb)
            if self._last_config is not None:
                core.update_configuration(*self._last_config)
            self._quarantined.discard(idx)
            self.partitioner.set_active(idx, True)
            # fresh pump for the rebuilt core (the fenced pump exits on
            # its stale epoch if the zombie ever unwedges)
            self.delivery[idx].revive(core)
        core.start()
        # re-admission happens at the next epoch — advance it now so the
        # rebuilt shard is not an idle passenger until the epoch timer
        # (which defaults to off) fires
        self.reseed_epoch()
        logger.info("shard %d rebuilt and re-admitted (epoch %d)", idx,
                    self.epoch)
        return True

    # ----------------------------------------------------------- repair pass
    def _on_skipped(self, shard_idx: int,
                    request: UpdateContainerSchedulingStateRequest) -> bool:
        """A shard declared an ask unplaceable on ITS nodes. Returns True
        when the SKIPPED is absorbed (repair migrated the ask to an
        untried shard — the full-fleet pass); False surfaces it.

        The whole migration runs under _mu — the lock every routing
        writer (ask submit, release, node moves) takes — so a concurrent
        pod release cannot interleave: either the release won _mu first
        (then _asks no longer holds the key and we surface), or we
        migrate first and the release's broadcast/pop reaches the target
        shard afterwards, cleaning up the re-submitted ask normally."""
        key = request.allocation_key
        now = time.time()
        with self._mu:
            ask = self._asks.get(key)
            if ask is None:
                return False
            # the full-fleet pass covers the ACTIVE shards: quarantined
            # shards own no nodes (their domains re-homed), so neither
            # their old "tried" marks nor their index count toward it
            active = set(range(self.n)) - self._quarantined
            with self._stats_mu:
                st = self._repair.setdefault(
                    key, {"tried": set(), "cool_until": 0.0})
                st["tried"] &= active
                st["tried"].add(shard_idx)
                exhausted = active <= st["tried"]
                cooling = now < st["cool_until"]
                if exhausted:
                    # full-fleet pass complete: genuinely unschedulable
                    # right now; cool down before the next round so
                    # saturation does not ping-pong the ask between
                    # shards every cycle
                    st["tried"] = {shard_idx}
                    st["cool_until"] = now + REPAIR_COOLDOWN_S
                    tried = None
                else:
                    tried = set(st["tried"])
            if tried is None:
                self._m_repair.inc(outcome="exhausted")
                # ROADMAP (d): every active shard refused the ask on FREE
                # capacity — post a victim credit on the ledger so the
                # shard it stays pending on may EVICT for it (the planner
                # bypasses its repair-only stance for credited keys)
                try:
                    self.ledger.post_victim_credit(key, shard_idx)
                    with self._stats_mu:
                        self._credited.add(key)
                    self.journey.annotate(key, victim_credit=shard_idx)
                except Exception:
                    logger.exception("victim credit post failed: %s", key)
                # journey terminal: every active shard tried and refused.
                # Not final forever — a post-cooldown bind "recovers" it
                self.journey.terminal(key, "skipped_fleetwide")
                return False
            if cooling:
                return False
            untried = [k for k in sorted(active) if k not in tried]
            if not untried:
                return False
            # prefer the untried shard with the most nodes (fleet
            # coverage per hop); ties by index for determinism
            target = max(untried,
                         key=lambda k: (self.fanout.count_for(k), -k))
            app_id = request.application_id
            self._ensure_guest_app_locked(app_id, target, None)
            self._ask_home[key] = target
            # pull the pending ask out of the reporting shard, then
            # re-submit to the target: _release_allocation pops a pending
            # ask without emitting a release (the allocation never
            # existed). The src release stays a DIRECT core call — the
            # ask must leave the reporting shard before the target's pump
            # can deliver its copy, or both shards would hold it pending
            # and could double-place. Safe: we run on the reporting
            # shard's own cycle thread (callbacks are emitted outside the
            # core lock), the core lock is reacquired briefly, and pumps
            # never hold a core lock while taking _mu, so no reverse edge
            # exists. The target delivery is an ordinary enqueue.
            from yunikorn_tpu.common.si import (AllocationRelease,
                                                TerminationType)

            self.shards[shard_idx].update_allocation(AllocationRequest(
                releases=[AllocationRelease(
                    application_id=app_id, allocation_key=key,
                    termination_type=TerminationType.STOPPED_BY_RM,
                    message="shard repair: migrating stranded ask")]))
            self._deliver(target, "update_allocation",
                          AllocationRequest(asks=[ask]))
            with self._stats_mu:
                st = self._repair.get(key)
                if st is not None:
                    st["tried"].add(target)
        self._m_repair.inc(outcome="migrated")
        self._m_asks.inc(shard=str(target))
        self.tracer.add("repair", 0, now, time.time(), key=key,
                        src=shard_idx, dst=target)
        self.journey.annotate(key, hop=f"repaired:s{shard_idx}->s{target}",
                              repaired_to=target)
        logger.info("shard repair: ask %s migrated s%d -> s%d", key,
                    shard_idx, target)
        return True

    # ------------------------------------------------------------- callbacks
    def _forget_asks(self, pairs: List[Tuple[str, str]]) -> None:
        """Drop routing/repair entries for asks a shard REJECTED (no
        release event will ever arrive for them). _mu is an RLock, so the
        repair path's inline re-submit rejecting on the same thread is
        safe."""
        with self._mu:
            for _app_id, key in pairs:
                self._asks.pop(key, None)
                self._ask_home.pop(key, None)
            with self._stats_mu:
                for _app_id, key in pairs:
                    self._repair.pop(key, None)
                credited = [key for _a, key in pairs
                            if key in self._credited]
                self._credited.difference_update(credited)
        for key in credited:
            # the ask is gone for good: its eviction right dies with it
            try:
                self.ledger.clear_victim_credit(key)
            except Exception:
                pass

    def _note_allocations(self, shard_idx: int, response) -> None:
        """Per-shard commit accounting + repair settlement (may run under
        the shard's core lock: touches _stats_mu only; the deferred
        Completed re-emit goes straight to the REAL callback — async on
        the shim side, so safe from any lock context)."""
        done_apps: List[str] = []
        uncredit: List[str] = []
        t_lc0 = time.time() if response.new else 0.0
        with self._stats_mu:
            for alloc in response.new:
                self._bound_per_shard[shard_idx] += 1
                self._alloc_shard[alloc.allocation_key] = (
                    shard_idx, alloc.application_id)
                self._allocs[alloc.allocation_key] = alloc
                self._m_bound.inc(shard=str(shard_idx))
                if alloc.allocation_key in self._credited:
                    self._credited.discard(alloc.allocation_key)
                    uncredit.append(alloc.allocation_key)
                if self._repair.pop(alloc.allocation_key, None) is not None:
                    self._repair_placed += 1
                    self._m_repair.inc(outcome="placed")
                home = self._app_home.get(alloc.application_id)
                if home is not None and home != shard_idx:
                    self._repair_allocs.setdefault(
                        alloc.application_id, set()).add(
                            alloc.allocation_key)
            for rel in response.released:
                self._alloc_shard.pop(rel.allocation_key, None)
                self._allocs.pop(rel.allocation_key, None)
                keys = self._repair_allocs.get(rel.application_id)
                if keys is not None:
                    keys.discard(rel.allocation_key)
                    if not keys:
                        self._repair_allocs.pop(rel.application_id, None)
                        # the home shard already decided Completed (we
                        # suppressed it while this allocation was live);
                        # the fleet view is done now — re-emit, or the
                        # shim waits forever
                        if rel.application_id in self._suppressed_apps:
                            self._suppressed_apps.discard(
                                rel.application_id)
                            done_apps.append(rel.application_id)
        for key in uncredit:
            # the credited ask placed after all — drop its standing
            # eviction right before any planner redeems it
            try:
                self.ledger.clear_victim_credit(key)
            except Exception:
                pass
        if response.new:
            # front-lane span: the fleet-level commit confirmation pass
            # (ledger re-attribution bookkeeping per committed batch)
            self.tracer.add("ledger_confirm", 0, t_lc0, time.time(),
                            allocs=len(response.new), shard=shard_idx)
        if done_apps and self.callback is not None:
            from yunikorn_tpu.common.si import (ApplicationResponse,
                                                UpdatedApplication)

            logger.info("re-emitting Completed for %s: last repaired "
                        "allocation released", done_apps)
            self.callback.update_application(ApplicationResponse(updated=[
                UpdatedApplication(application_id=a, state="Completed",
                                   message="application completed")
                for a in done_apps]))

    def _filter_app_updates(self, shard_idx: int, response):
        """Suppress app-Completed updates the reporting shard cannot decide
        alone: while repaired allocations of the app live in OTHER shards,
        the app is not done — only the fleet view knows."""
        if not response.updated:
            return response
        kept = []
        for upd in response.updated:
            if upd.state == "Completed":
                with self._stats_mu:
                    live = self._repair_allocs.get(upd.application_id)
                    if live:
                        self._suppressed_completions += 1
                        # remember: core emits Completed only once (the
                        # state transition); _note_allocations re-emits
                        # when the last repaired allocation releases
                        self._suppressed_apps.add(upd.application_id)
                        logger.info(
                            "suppressing Completed for %s from s%d: %d "
                            "repaired allocation(s) live elsewhere",
                            upd.application_id, shard_idx, len(live))
                        continue
            kept.append(upd)
        if not (kept or response.accepted or response.rejected):
            return None
        return dataclasses.replace(response, updated=kept)


# ---------------------------------------------------------------------------
# Factory: the conf-driven entry point
# ---------------------------------------------------------------------------
def resolve_shards(value) -> int:
    """solver.shards -> shard count. "auto" resolves to 1 (sharding is
    opt-in: the single-shard scheduler stays bit-identical to the pre-shard
    one, and auto-scaling by fleet size is a follow-up once the parity
    bench has hardware numbers); integers clamp to [1, 64]."""
    s = str(value).strip().lower()
    if s in ("", "auto"):
        return 1
    try:
        return max(1, min(int(s), 64))
    except ValueError:
        logger.warning("invalid solver.shards %r; using 1", value)
        return 1


def make_core_scheduler(cache, *, shards=1, interval: float = 0.1,
                        solver_policy=None, solver_options=None,
                        trace_spans: int = 4096, supervisor_options=None,
                        slo_options=None, epoch_seconds: float = 0.0,
                        failover_options=None, journey_capacity: int = 8192,
                        flightrec_options=None,
                        delivery_high_water: int = 1024,
                        ledger_endpoint: str = "",
                        ledger_serve: bool = False,
                        ledger_client_options=None, host_id: str = ""):
    """Build the scheduler for a shard count: a plain CoreScheduler for 1
    (bit-identical to the pre-shard scheduler — no ledger, no views, no
    namespaces, no failover machinery; ledger-service flags are ignored,
    pinned by test), the sharded front end for N >= 2."""
    n = shards if isinstance(shards, int) else resolve_shards(shards)
    if n <= 1:
        if ledger_endpoint or ledger_serve:
            logger.warning("ledger service requested with shards=1; "
                           "ignored — the single-shard scheduler has no "
                           "ledger coupling to move behind a socket")
        return CoreScheduler(cache, interval=interval,
                             solver_policy=solver_policy,
                             solver_options=solver_options,
                             trace_spans=trace_spans,
                             supervisor_options=supervisor_options,
                             slo_options=slo_options,
                             journey_capacity=journey_capacity,
                             flightrec_options=flightrec_options)
    return ShardedCoreScheduler(
        cache, n, interval=interval, solver_policy=solver_policy,
        solver_options=solver_options, trace_spans=trace_spans,
        supervisor_options=supervisor_options, slo_options=slo_options,
        epoch_seconds=epoch_seconds, failover_options=failover_options,
        journey_capacity=journey_capacity,
        flightrec_options=flightrec_options,
        delivery_high_water=delivery_high_water,
        ledger_endpoint=ledger_endpoint, ledger_serve=ledger_serve,
        ledger_client_options=ledger_client_options, host_id=host_id)
