"""Ledger-as-a-service: the GlobalQuotaLedger behind a real RPC boundary.

Rounds 16-21 coupled the sharded control plane through ONE in-process
`GlobalQuotaLedger` object — exact, atomic, and useless the moment a shard
lives in another process (ROADMAP (b): "the RPC boundary itself"). This
module is that boundary:

  LedgerServer
      A thread serving length-prefixed JSON frames over a local TCP socket.
      Every op carries an idempotency key (``client_id:seq``) and a
      monotonic per-client sequence number, so the retry/duplicate/reorder
      abuse a lossy network produces collapses to exactly-once semantics:
      a duplicate frame replays the CACHED response (never the side
      effect), and a frame arriving after a LATER op on the same
      allocation key is dropped as stale (a late ``reserve`` must never
      re-hold quota a ``release`` already dropped — the ledger's own
      key-idempotent commit semantics cover the remaining shapes).

  LedgerClient
      Implements the exact ledger surface the cores consume
      (reserve/reserve_many/commit/release/release_reservation/audit/
      app-slot ops ride the same key space) with per-op deadlines, capped
      exponential backoff with jitter, and a circuit breaker reusing the
      robustness/supervisor.py ladder conventions. No call ever blocks on
      a dead socket past its deadline budget: once the breaker opens the
      client answers from DEGRADED mode instantly.

  Degraded mode (the availability contract)
      With the ledger unreachable past the breaker budget the client falls
      back to the round-21 DeviceUsageMirror's ``provably_exceeds``
      pre-check plus a conservative local reservation cache — degraded
      admission can only over-admit PENDING work (the mirror carries
      confirmed usage; local pending charges stack on top), never
      confirmed usage, so the commit-time authority re-converges exactly:
      on reconnect the client replays its unacked journal in sequence
      order and ``audit()`` comes back bit-equal. ``failClosed`` flips the
      policy to reject every admission while degraded (quota exactness
      over availability).

  Liveness authority (cross-host failover, ROADMAP (e))
      Each shard host heartbeats a lease on its ledger connection
      (``heartbeat_host``); the lease table doubles as the fleet's
      liveness authority — robustness/failover.HostLeaseMonitor quarantines
      an expired host's shards through the round-18 quarantine/re-home
      machinery.

Transport faults are injected through robustness/faults.NetFaultPlane
(drop/delay/duplicate/partition/flap), driven from ``trace_replay
--fault netsplit|ledger-lag`` and the chaos suites.

``shards=1`` and in-process multi-shard never construct this module —
the direct ledger object stays byte-identical (pinned by test).
"""
from __future__ import annotations

import collections
import dataclasses
import json
import random
import socket
import struct
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from yunikorn_tpu.log.logger import log
from yunikorn_tpu.obs.metrics import MS_BUCKETS
from yunikorn_tpu.robustness.faults import NetFaultPlane, NetPartitioned
from yunikorn_tpu.robustness.supervisor import CircuitBreaker

logger = log("core.ledger_service")

# frame = 4-byte big-endian payload length + UTF-8 JSON payload
_LEN = struct.Struct(">I")
MAX_FRAME = 32 * 1024 * 1024

# ledger_mode gauge encoding (fixed, documented in COMPONENTS.md)
MODE_LOCAL, MODE_REMOTE, MODE_DEGRADED, MODE_FAIL_CLOSED = (
    "local", "remote", "degraded", "fail_closed")
MODE_GAUGE = {MODE_LOCAL: 0, MODE_REMOTE: 1, MODE_DEGRADED: 2,
              MODE_FAIL_CLOSED: 3}

# ops fenced by the per-(client, key) sequence: a frame for one of these
# arriving with a seq below the key's last APPLIED seq is a stale reorder
# and must not re-apply (the duplicate cache handles equal seqs)
_KEYED_OPS = ("reserve", "commit", "release", "release_reservation",
              "post_victim_credit", "consume_victim_credit",
              "clear_victim_credit")


def _dump(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("ledger peer closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise ConnectionError(f"ledger frame too large ({n} bytes)")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


def _charges_from_wire(charges) -> list:
    """JSON round-trips tuples as lists; the ledger's `for k, v in items`
    walks accept either, but normalizing to tuples keeps reservation
    records hashable/comparable with the in-process path."""
    out = []
    for tid, limit, amount in charges or ():
        out.append((tid, [tuple(p) for p in limit],
                    [tuple(p) for p in amount]))
    return out


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------
class LedgerServer:
    """Serves one GlobalQuotaLedger over length-prefixed JSON frames.

    One accept thread plus one handler thread per connection (connection
    count is O(hosts), not O(asks) — each host process keeps a single
    persistent connection). The idempotency table holds the last
    `seen_cap` op results per client; the per-(client, key) applied-seq
    map fences stale reorders."""

    def __init__(self, ledger, host: str = "127.0.0.1", port: int = 0,
                 seen_cap: int = 65536,
                 faults: Optional[NetFaultPlane] = None):
        self.ledger = ledger
        self._host = host
        self._port = port
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._mu = threading.Lock()
        self._seen: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._seen_cap = seen_cap
        self._key_seq: Dict[Tuple[str, str], int] = {}
        self.faults = faults or NetFaultPlane()
        self.requests = 0
        self.duplicates = 0
        self.stale_drops = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(64)
        self._sock = s
        self._port = s.getsockname()[1]
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ledger-server", daemon=True)
        self._accept_thread.start()
        logger.info("ledger service listening on %s:%d", self._host,
                    self._port)
        return self._host, self._port

    @property
    def endpoint(self) -> str:
        return f"{self._host}:{self._port}"

    def stop(self) -> None:
        self._stop.set()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                return
            try:
                conn, _addr = sock.accept()
            except OSError:
                return  # closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="ledger-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                req = _recv_frame(conn)
                # server-side fault plane: drop/delay/partition before the
                # op applies — the client sees a hung/failed frame exactly
                # like a lossy network would produce
                try:
                    dups = self.faults.on_frame(req.get("op", "?"))
                except NetPartitioned:
                    conn.close()
                    return
                resp = self._apply(req)
                for _ in range(max(1, dups)):
                    _send_frame(conn, _dump(resp))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------- dispatch
    def _apply(self, req: dict) -> dict:
        op = req.get("op", "")
        op_id = req.get("id")
        client = req.get("client", "")
        seq = int(req.get("seq", 0))
        args = req.get("args") or {}
        self.requests += 1
        if op_id is not None:
            with self._mu:
                cached = self._seen.get(op_id)
                if cached is not None:
                    self.duplicates += 1
                    return cached
                key = args.get("key")
                if op in _KEYED_OPS and key is not None:
                    last = self._key_seq.get((client, key), -1)
                    if seq < last:
                        # stale reorder: a LATER op on this key already
                        # applied; the safe no-op answer is success (the
                        # later op's effect stands)
                        self.stale_drops += 1
                        resp = {"ok": True, "result": True, "stale": True}
                        self._remember(op_id, resp)
                        return resp
        try:
            result = self._dispatch(op, args, client, seq)
            resp = {"ok": True, "result": result}
            if op in ("reserve", "reserve_many"):
                resp["counters"] = {
                    "contention_retries": self.ledger.contention_retries,
                    "reserve_held": self.ledger.reserve_held,
                }
        except Exception as exc:  # surfaced to the client as an error frame
            logger.exception("ledger op %s failed", op)
            resp = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        if op_id is not None:
            with self._mu:
                self._remember(op_id, resp)
                key = args.get("key")
                if op in _KEYED_OPS and key is not None and resp.get("ok"):
                    prev = self._key_seq.get((client, key), -1)
                    if seq > prev:
                        self._key_seq[(client, key)] = seq
        return resp

    def _remember(self, op_id: str, resp: dict) -> None:
        self._seen[op_id] = resp
        while len(self._seen) > self._seen_cap:
            self._seen.popitem(last=False)

    def _dispatch(self, op: str, args: dict, client: str, seq: int):
        led = self.ledger
        if op == "ping":
            return "pong"
        if op == "reserve":
            return led.reserve(args["key"], _charges_from_wire(
                args.get("charges")))
        if op == "reserve_many":
            # batch fencing: each entry checks its own key's applied seq
            # (the batch shares one frame seq) — a stale key no-ops True
            items = []
            out_idx: List[Optional[bool]] = []
            for key, charges in args.get("items") or ():
                last = self._key_seq.get((client, key), -1)
                if seq < last:
                    self.stale_drops += 1
                    out_idx.append(True)
                else:
                    self._key_seq[(client, key)] = seq
                    out_idx.append(None)
                    items.append((key, _charges_from_wire(charges)))
            results = led.reserve_many(items)
            it = iter(results)
            return [nxt if nxt is not None else next(it)
                    for nxt in out_idx]
        if op == "commit":
            led.commit(args["key"], _charges_from_wire(args.get("charges")))
            return True
        if op == "release":
            led.release(args["key"])
            return True
        if op == "release_reservation":
            led.release_reservation(args["key"])
            return True
        if op == "audit":
            return led.audit()
        if op == "stats":
            return led.stats()
        if op == "usage_snapshot":
            return led.usage_snapshot()
        if op == "drain_deltas":
            # wire shape: [[tid, [[rk, v], ...], sign], ...]
            return [[tid, [list(p) for p in items], sign]
                    for tid, items, sign in led.drain_deltas()]
        if op == "requeue_deltas":
            led.requeue_deltas([
                (tid, tuple(tuple(p) for p in items), sign)
                for tid, items, sign in args.get("deltas") or ()])
            return True
        if op == "enable_journal":
            led.enable_journal()
            return True
        if op == "post_victim_credit":
            led.post_victim_credit(args["key"], int(args.get("shard", 0)))
            return True
        if op == "victim_credits":
            return led.victim_credits(int(args.get("shard", 0)))
        if op == "consume_victim_credit":
            return led.consume_victim_credit(args["key"])
        if op == "clear_victim_credit":
            led.clear_victim_credit(args["key"])
            return True
        if op == "heartbeat_host":
            led.heartbeat_host(args["host"])
            return True
        if op == "register_host_shards":
            led.register_host_shards(args["host"],
                                     [int(s) for s in args.get("shards", ())])
            return True
        if op == "expired_hosts":
            return [[h, list(s)]
                    for h, s in led.expired_hosts(float(args["ttl_s"]))]
        if op == "host_leases":
            return led.host_leases()
        raise ValueError(f"unknown ledger op {op!r}")


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class LedgerClientOptions:
    """RPC-boundary knobs (conf robustness.ledger* keys).

    deadline_s bounds ONE socket round-trip; an op retries up to
    max_retries times under capped exponential backoff with full jitter
    (supervisor ladder convention), so the worst-case wall an op can hold
    a caller is deadline * (retries+1) + backoff — after which the breaker
    is open and every subsequent call answers from degraded mode without
    touching the socket."""
    deadline_s: float = 2.0
    max_retries: int = 2
    backoff_base_s: float = 0.02
    backoff_cap_s: float = 0.25
    breaker_threshold: int = 3
    probe_interval_s: float = 1.0
    fail_closed: bool = False
    lease_ttl_s: float = 15.0

    @classmethod
    def from_conf(cls, conf) -> "LedgerClientOptions":
        return cls(
            deadline_s=max(float(getattr(
                conf, "robustness_ledger_deadline_s", 2.0)), 0.01),
            fail_closed=(str(getattr(
                conf, "robustness_ledger_fail_closed", "false")) == "true"),
            lease_ttl_s=max(float(getattr(
                conf, "robustness_ledger_lease_ttl_s", 15.0)), 0.1),
        )


class LedgerClient:
    """GlobalQuotaLedger surface over the socket, with the fault plane.

    Thread-safe: RPCs serialize on one persistent connection under
    `_io_mu` (ledger ops are sub-millisecond; the round-20 mirror already
    took the per-ask hot path off this boundary). Degraded-mode state and
    the unacked journal live under `_mu` (leaf lock)."""

    def __init__(self, endpoint: str, options: Optional[
            LedgerClientOptions] = None, registry=None,
            faults: Optional[NetFaultPlane] = None, client_id: str = ""):
        host, _, port = endpoint.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.options = options or LedgerClientOptions()
        self.client_id = client_id or uuid.uuid4().hex[:12]
        self.netfaults = faults or NetFaultPlane()
        self._io_mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()
        self._seq = 0
        self.breaker = CircuitBreaker(self.options.breaker_threshold,
                                      self.options.probe_interval_s)
        self._mode = MODE_REMOTE
        # unacked mutating ops, seq -> frame; replayed FIFO on reconnect
        self._unacked: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        # degraded-mode conservative reservation cache: key -> charges of
        # every locally-admitted, not-yet-replayed reservation
        self._local_charges: Dict[str, list] = {}
        self._mirror = None
        self._flightrec = None
        # last-known remote answers served while degraded (never block)
        self._last_audit: List[str] = []
        self._last_stats: dict = {}
        self._last_usage: Dict[str, Dict[str, int]] = {}
        self.contention_retries = 0
        self.reserve_held = 0
        self.degraded_admits = 0
        self.degraded_rejects = 0
        self.replayed_ops = 0
        self._m_latency = self._m_retries = self._g_mode = None
        if registry is not None:
            self.attach_metrics(registry)

    # ------------------------------------------------------------- plumbing
    def attach_metrics(self, registry) -> None:
        self._m_latency = registry.histogram(
            "ledger_rpc_latency_ms",
            "round-trip latency of one ledger RPC frame by op (successful "
            "attempts only; retries count separately)",
            labelnames=("op",), buckets=MS_BUCKETS)
        self._m_retries = registry.counter(
            "ledger_rpc_retries_total",
            "ledger RPC attempts that failed and were retried or shed, by "
            "op and reason (timeout = per-op deadline, conn = transport "
            "error/partition, breaker = circuit open, error = server-side "
            "op failure)",
            labelnames=("op", "reason"))
        self._g_mode = registry.gauge(
            "ledger_mode",
            "quota-ledger coupling mode (0=local in-process, 1=remote RPC, "
            "2=degraded local admission, 3=fail_closed rejecting)")
        self._g_mode.set(MODE_GAUGE[self._mode])

    def attach_flightrec(self, flightrec) -> None:
        self._flightrec = flightrec

    def attach_mirror(self, mirror) -> None:
        """Mirror the in-process attach contract: bind, then enable the
        authority's journal so drain_deltas starts flowing (seeded with
        current usage for a bit-equal late attach)."""
        mirror.bind_ledger(self)
        self._mirror = mirror
        self._call("enable_journal", {}, mutating=False, default=True)

    @property
    def mode(self) -> str:
        with self._mu:
            return self._mode

    def _set_mode(self, mode: str) -> None:
        """Caller holds _mu. Publishes the gauge + flight-recorder trigger
        outside the lock via the returned thunk pattern kept inline — the
        recorder trigger only fires on ENTERING a degraded mode."""
        prev, self._mode = self._mode, mode
        if self._g_mode is not None:
            self._g_mode.set(MODE_GAUGE[mode])
        if mode != prev:
            logger.warning("ledger client mode: %s -> %s", prev, mode)
            if (mode in (MODE_DEGRADED, MODE_FAIL_CLOSED)
                    and self._flightrec is not None):
                fr = self._flightrec
                threading.Thread(
                    target=lambda: fr.record(
                        "ledger_degraded",
                        reason=f"breaker open; mode={mode}"),
                    name="ledger-flightrec", daemon=True).start()

    def _next_frame(self, op: str, args: dict, mutating: bool) -> dict:
        with self._mu:
            self._seq += 1
            seq = self._seq
        frame = {"op": op, "args": args, "client": self.client_id,
                 "seq": seq, "id": f"{self.client_id}:{seq}"}
        if mutating:
            with self._mu:
                self._unacked[seq] = frame
        return frame

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(
            self._addr, timeout=self.options.deadline_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _rpc_once(self, frame: dict) -> dict:
        """One framed round-trip under the per-op deadline. Raises
        NetPartitioned / ConnectionError / socket.timeout on the fault
        paths; the caller owns retries and breaker accounting."""
        op = frame.get("op", "?")
        dups = self.netfaults.on_frame(op)  # may sleep or raise
        with self._io_mu:
            if self._sock is None:
                self._sock = self._dial()
            sock = self._sock
            sock.settimeout(self.options.deadline_s)
            try:
                payload = _dump(frame)
                for _ in range(max(1, dups)):
                    _send_frame(sock, payload)
                resp = _recv_frame(sock)
                for _ in range(max(1, dups) - 1):
                    # duplicated frames produce duplicated (cached)
                    # responses: drain them so the stream stays aligned
                    _recv_frame(sock)
                return resp
            except (ConnectionError, OSError, socket.timeout):
                try:
                    sock.close()
                except OSError:
                    pass
                self._sock = None
                raise

    def _replay_unacked_locked_out(self) -> None:
        """Resend every unacked mutating op in sequence order (called with
        NO locks held; races with new ops are benign — the server's
        duplicate cache and key-seq fence absorb any interleaving)."""
        with self._mu:
            pending = list(self._unacked.items())
        for seq, frame in pending:
            try:
                resp = self._rpc_once(frame)
            except (NetPartitioned, ConnectionError, OSError,
                    socket.timeout):
                return  # still down; journal keeps the rest
            if resp.get("ok"):
                with self._mu:
                    self._unacked.pop(seq, None)
                self.replayed_ops += 1
        with self._mu:
            if not self._unacked:
                # authority has the full history again: local pending
                # charges are now reflected in its reservation table
                self._local_charges.clear()

    def _call(self, op: str, args: dict, mutating: bool, default,
              key: Optional[str] = None, degraded_fn=None):
        """The supervised RPC path: breaker gate -> bounded retries with
        capped exponential backoff + full jitter -> degraded fallback."""
        opts = self.options
        frame = self._next_frame(op, args, mutating)
        attempts = 0
        while True:
            now = time.time()
            with self._mu:
                allowed = self.breaker.allow(now)
                was_degraded = self._mode in (MODE_DEGRADED,
                                              MODE_FAIL_CLOSED)
            if not allowed:
                if self._m_retries is not None:
                    self._m_retries.inc(op=op, reason="breaker")
                return self._degraded(op, frame, mutating, default,
                                      key, degraded_fn)
            if was_degraded:
                # half-open probe admitted: heal the journal FIRST so the
                # authority sees ops in sequence order
                self._replay_unacked_locked_out()
            t0 = time.perf_counter()
            try:
                resp = self._rpc_once(frame)
            except (NetPartitioned, ConnectionError, OSError,
                    socket.timeout) as exc:
                reason = ("timeout" if isinstance(exc, socket.timeout)
                          else "conn")
                if self._m_retries is not None:
                    self._m_retries.inc(op=op, reason=reason)
                attempts += 1
                opened = False
                with self._mu:
                    opened = self.breaker.record_failure(time.time())
                if opened or attempts > opts.max_retries:
                    with self._mu:
                        self.breaker.record_failure(time.time(), hard=True)
                    return self._degraded(op, frame, mutating, default,
                                          key, degraded_fn)
                # capped exponential backoff, full jitter (supervisor
                # ladder convention: base * 2^(attempts-1) * rand)
                delay = min(opts.backoff_base_s * (2 ** (attempts - 1)),
                            opts.backoff_cap_s) * random.random()
                if delay > 0:
                    time.sleep(delay)
                continue
            if self._m_latency is not None:
                self._m_latency.observe(
                    (time.perf_counter() - t0) * 1000.0, op=op)
            with self._mu:
                self.breaker.record_success()
                if self._mode != MODE_REMOTE:
                    self._set_mode(MODE_REMOTE)
                if mutating:
                    self._unacked.pop(frame["seq"], None)
                    if key is not None:
                        self._local_charges.pop(key, None)
            if not resp.get("ok"):
                if self._m_retries is not None:
                    self._m_retries.inc(op=op, reason="error")
                logger.error("ledger op %s refused: %s", op,
                             resp.get("error"))
                return default
            counters = resp.get("counters")
            if counters:
                self.contention_retries = int(
                    counters.get("contention_retries",
                                 self.contention_retries))
                self.reserve_held = int(
                    counters.get("reserve_held", self.reserve_held))
            return resp.get("result", default)

    def _degraded(self, op: str, frame: dict, mutating: bool, default,
                  key: Optional[str], degraded_fn):
        with self._mu:
            self._set_mode(MODE_FAIL_CLOSED if self.options.fail_closed
                           else MODE_DEGRADED)
        if degraded_fn is not None:
            return degraded_fn(frame)
        if not mutating:
            return default
        # plain mutating op (commit/release/credit): stays journaled for
        # the reconnect replay; locally assume success
        return True

    # ---------------------------------------------------- degraded admission
    def _mirror_usage(self) -> Dict[str, Dict[str, int]]:
        if self._mirror is not None:
            try:
                return self._mirror.host_usage()
            except Exception:
                return self._last_usage
        return self._last_usage

    def _degraded_reserve_one(self, key: str, charges: list,
                              usage: Dict[str, Dict[str, int]],
                              pending: Dict[str, Dict[str, int]]) -> bool:
        """Conservative local admission under _mu: confirmed usage (the
        mirror's last fold — can only UNDERSTATE by in-flight commits the
        authority already accepted, i.e. over-admit PENDING) plus every
        locally-pending reservation plus this ask must fit the limit."""
        if self.options.fail_closed:
            self.degraded_rejects += 1
            return False
        if key in self._local_charges:
            return True
        for tid, limit, amount in charges:
            used = usage.get(tid, {})
            pend = pending.get(tid, {})
            amt = dict(amount)
            for rk, lim_v in limit:
                if (used.get(rk, 0) + pend.get(rk, 0)
                        + amt.get(rk, 0)) > lim_v:
                    self.degraded_rejects += 1
                    return False
        self._local_charges[key] = charges
        for tid, _limit, amount in charges:
            acc = pending.setdefault(tid, {})
            for rk, v in amount:
                acc[rk] = acc.get(rk, 0) + v
        self.degraded_admits += 1
        return True

    def _pending_sums(self) -> Dict[str, Dict[str, int]]:
        pending: Dict[str, Dict[str, int]] = {}
        for ch in self._local_charges.values():
            for tid, _limit, amount in ch:
                acc = pending.setdefault(tid, {})
                for rk, v in amount:
                    acc[rk] = acc.get(rk, 0) + v
        return pending

    def _degraded_reserve(self, frame: dict) -> bool:
        args = frame["args"]
        key, charges = args["key"], args.get("charges") or []
        if not charges:
            with self._mu:
                self._unacked.pop(frame["seq"], None)
            return True
        usage = self._mirror_usage()
        with self._mu:
            ok = self._degraded_reserve_one(key, charges, usage,
                                            self._pending_sums())
            if not ok:
                # refused admissions must not replay later as reserves
                self._unacked.pop(frame["seq"], None)
        return ok

    def _degraded_reserve_many(self, frame: dict) -> List[bool]:
        items = frame["args"].get("items") or []
        usage = self._mirror_usage()
        out: List[bool] = []
        with self._mu:
            pending = self._pending_sums()
            admitted: List[Tuple[str, list]] = []
            for key, charges in items:
                if not charges:
                    out.append(True)
                    continue
                ok = self._degraded_reserve_one(key, charges, usage,
                                                pending)
                out.append(ok)
                if ok:
                    admitted.append((key, charges))
            # the batch frame is NOT replayable as-is (some entries were
            # refused): swap the journal entry for per-key reserve frames
            self._unacked.pop(frame["seq"], None)
        for key, charges in admitted:
            self._next_frame("reserve", {"key": key, "charges": charges},
                             mutating=True)
        return out

    # ------------------------------------------------------------ ledger API
    def reserve(self, key: str, charges: list) -> bool:
        if not charges:
            return True
        return self._call("reserve", {"key": key, "charges": charges},
                          mutating=True, default=False, key=key,
                          degraded_fn=self._degraded_reserve)

    def reserve_many(self, items: list) -> List[bool]:
        if not items:
            return []
        items = [(k, list(c)) for k, c in items]
        return self._call("reserve_many", {"items": items}, mutating=True,
                          default=[bool(not c) for _k, c in items],
                          degraded_fn=self._degraded_reserve_many)

    def commit(self, key: str, charges: list) -> None:
        self._call("commit", {"key": key, "charges": charges},
                   mutating=True, default=True, key=key)

    def release(self, key: str) -> None:
        with self._mu:
            self._local_charges.pop(key, None)
        self._call("release", {"key": key}, mutating=True, default=True,
                   key=key)

    def release_reservation(self, key: str) -> None:
        with self._mu:
            self._local_charges.pop(key, None)
        self._call("release_reservation", {"key": key}, mutating=True,
                   default=True, key=key)

    def audit(self) -> List[str]:
        out = self._call("audit", {}, mutating=False, default=None)
        if out is None:
            return list(self._last_audit)
        self._last_audit = list(out)
        return out

    def stats(self) -> dict:
        out = self._call("stats", {}, mutating=False, default=None)
        if out is None:
            with self._mu:
                out = dict(self._last_stats)
                out["mode"] = self._mode
                out["unacked"] = len(self._unacked)
                out["degraded_admits"] = self.degraded_admits
            return out
        self._last_stats = dict(out)
        out = dict(out)
        out["mode"] = self.mode
        with self._mu:
            out["unacked"] = len(self._unacked)
        out["degraded_admits"] = self.degraded_admits
        return out

    def usage_snapshot(self) -> Dict[str, Dict[str, int]]:
        out = self._call("usage_snapshot", {}, mutating=False, default=None)
        if out is None:
            return dict(self._last_usage)
        self._last_usage = {tid: dict(items) for tid, items in out.items()}
        return self._last_usage

    def drain_deltas(self) -> list:
        out = self._call("drain_deltas", {}, mutating=False, default=())
        return [(tid, tuple(tuple(p) for p in items), sign)
                for tid, items, sign in out]

    def requeue_deltas(self, deltas: list) -> None:
        self._call("requeue_deltas", {"deltas": [
            [tid, [list(p) for p in items], sign]
            for tid, items, sign in deltas]}, mutating=True, default=True)

    # ------------------------------------------------------- victim credits
    def post_victim_credit(self, key: str, shard: int) -> None:
        self._call("post_victim_credit", {"key": key, "shard": shard},
                   mutating=True, default=True, key=key)

    def victim_credits(self, shard: int) -> List[str]:
        return self._call("victim_credits", {"shard": shard},
                          mutating=False, default=[])

    def consume_victim_credit(self, key: str) -> bool:
        return bool(self._call("consume_victim_credit", {"key": key},
                               mutating=True, default=False, key=key))

    def clear_victim_credit(self, key: str) -> None:
        self._call("clear_victim_credit", {"key": key}, mutating=True,
                   default=True, key=key)

    # ------------------------------------------------------------ liveness
    def heartbeat_host(self, host: str) -> None:
        # NOT journaled: a stale heartbeat replayed after a partition would
        # assert liveness for exactly the window the host was dead
        self._call("heartbeat_host", {"host": host}, mutating=False,
                   default=True)

    def register_host_shards(self, host: str, shards: List[int]) -> None:
        self._call("register_host_shards",
                   {"host": host, "shards": list(shards)},
                   mutating=True, default=True)

    def expired_hosts(self, ttl_s: float) -> List[Tuple[str, List[int]]]:
        out = self._call("expired_hosts", {"ttl_s": ttl_s},
                         mutating=False, default=[])
        return [(h, [int(s) for s in shards]) for h, shards in out]

    def host_leases(self) -> dict:
        return self._call("host_leases", {}, mutating=False, default={})

    def close(self) -> None:
        with self._io_mu:
            sock, self._sock = self._sock, None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
