"""Array-form admission gate: quota + user/group-limit admission as grouped
prefix-scan arithmetic instead of a per-ask Python walk.

The legacy gate (CoreScheduler._gate_admit_legacy) walks every pending ask:
sort its queue, walk its quota chain, check every applicable user/group
limit, fold the admission into per-queue/per-user in-cycle accumulators.
That is O(pods x chain depth) of pure Python on the host critical path —
24 ms at 1k pods, i.e. ~1.2 s extrapolated to the 50k-pod north star, which
dwarfs the device solve it feeds. POP (arxiv 2110.11927) and CvxCluster
(arxiv 2605.01614) both make the same observation about granular allocators:
per-entity host logic must become batched array arithmetic or it becomes the
bottleneck the moment the solve stops being one.

This module reformulates the EXACT same decision procedure:

  rank    one np.lexsort over (queue order, adjusted priority desc, app
          submit time, ask seq) reproduces the legacy nested sort bit-for-bit
          (queue order = the legacy (-best_prio, fair_share, name) tuple sort)
  admit   every quota node / user-limit / group-limit becomes a *tracker*: a
          budget vector (max - allocated, +inf for unconstrained resources)
          plus the ordered member asks that would consume it. Admission is an
          iterative vectorized scan: per pass, a segmented cumulative sum
          gives every undecided ask its would-be usage in every tracker,
          OVER-estimating the sequential loop's running usage (it counts
          every undecided predecessor, a superset of the truly-admitted
          ones). That over-estimate is one-sided, which finalizes almost
          everything in one pass:
            - every non-violator admits (fits under the over-estimate ⟹
              fits under the exact usage),
            - every violator that is the FIRST violator in all of its
              trackers holds (its prefix contains only admitted asks, so it
              is exact),
            - the remaining violators — blocked by an earlier violator in
              some shared tracker, whose removal could free budget — defer
              to the next pass, which recomputes exact prefixes over just
              that (tiny) remainder with the membership arrays compacted,
            - a definite-hold sweep removes every deferred ask whose request
              alone no longer fits the finalized usage (the saturated-queue
              fast path).
          Real traces converge in a handful of passes; a pathological trace
          falls through to an exact per-ask finish over the (few) undecided
          leftovers.

Semantics pinned against the legacy loop by tests/test_gate_vectorized.py:
identical admitted set, identical global order, identical held count — on
plain, quota, user/group-limit, gang and pipelined (seed_admissions /
exclude_keys) traces. The legacy loop itself lives here too (legacy_admit):
it is the differential oracle the tests and the optional verify mode run the
vectorized result against, and the fallback for cycles the exact int64
arithmetic cannot represent (GateFallback).
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from yunikorn_tpu.common.resource import Resource

# int64 budget sentinel for "this resource is unconstrained by this tracker".
# Strictly above the largest reachable cumulative sum (see the caps below),
# so an unconstrained column can never raise a spurious violation.
_INF = np.int64(1) << 62
# budget components are compared, never summed: cap them at 2^61
_MAX_BUDGET = 1 << 61
# request components ARE summed over the whole batch; 2^42 (≈4 TiB of bytes,
# 4e12 of any raw unit) caps the worst-case 2^18-ask cumulative sum at 2^60,
# well inside int64 with the base usage added on top. Duplicated-group
# charge weights multiply that bound — the admit phase re-checks
# w_max x n against the same ceiling before scanning.
_MAX_REQ = 1 << 42
# vectorized passes before conceding to the exact per-ask finish
_MAX_PASSES = 128
# batch-size ceiling: n * _MAX_REQ must stay below _INF so an unconstrained
# column's cumulative sum can never trip a spurious violation
_MAX_ASKS = 1 << 18


def _res_items(ask):
    """ask -> its resource item view (map()-friendly request-shape probe)."""
    return ask.resource.resources.items()


class GateFallback(Exception):
    """The array gate cannot represent this cycle exactly (oversized
    quantities); the caller must run the legacy loop instead."""


def fits_quota_with(quota_chain, cycle_extra: Dict[str, Resource],
                    req: Resource) -> bool:
    """fits_quota overlaying the in-cycle per-queue-node admissions.

    quota_chain holds only the ancestors that actually configure a max.
    """
    for q in quota_chain:
        extra = cycle_extra.get(q.full_name, Resource())
        if not q.allocated.add(extra).add(req).within_limit(q.config.max_resource):
            return False
    return True


def ledger_charges(leaf, user: str, groups, resource: Resource) -> list:
    """Tracker charges one allocation applies to the shared cross-shard
    quota ledger (core/shard.GlobalQuotaLedger): one entry per LIMITED
    tracker on the leaf's ancestor chain — queue max_resource nodes plus
    every applicable user/group limit — as (tracker_id, limit_items,
    amount_items) with plain-int item tuples (the ledger's arithmetic is
    exact python-int, the same integers the gate's int64 trackers carry).

    Unlimited trackers charge nothing: a fleet with no quotas configured
    produces an empty list and the ledger's reserve is a no-op — the
    sharded gate then costs nothing over the single-shard one. Mirrors
    fits_quota/fits_user_limit's applicability rules exactly (wildcard and
    named user/group lists; group limits charge the GROUP aggregate)."""
    if leaf is None:
        return []
    amount = tuple(resource.resources.items())
    if not amount:
        return []
    out = []
    for q in leaf.ancestors_and_self():
        if q.config.max_resource is not None:
            out.append((f"q|{q.full_name}",
                        tuple(q.config.max_resource.resources.items()),
                        amount))
        for i, lim in enumerate(q.config.limits):
            if lim.max_resources is None:
                continue
            lim_items = tuple(lim.max_resources.resources.items())
            if "*" in lim.users or user in lim.users:
                out.append((f"u|{q.full_name}|{i}|{user}", lim_items, amount))
            for g in groups:
                if g in lim.groups or "*" in lim.groups:
                    out.append((f"g|{q.full_name}|{i}|{g}", lim_items,
                                amount))
    return out


APP_SLOT_KEY = "__apps__"


def app_slot_charges(leaf, user: str, groups) -> list:
    """App-COUNT tracker charges one application registration applies to
    the shared cross-shard ledger (core/shard.GlobalQuotaLedger): one entry
    per ancestor queue with maxApplications plus every applicable user/group
    maxApplications limit, each charging one synthetic `__apps__` unit.

    The per-shard subtree_app_count / fits_user_app_limit checks see only
    the shard's OWN registrations — with N shards each admits up to the
    full max locally and the fleet overshoots by up to Nx. Registering
    through these charges (reserve+confirm keyed "app|<id>", released on
    app removal) makes maxApplications exact fleet-wide. Guest
    registrations from the stranded-ask repair path charge NOTHING — the
    home shard already holds the app's slot. Mirrors fits_user_app_limit's
    applicability rules (wildcard + named users; groups charge the GROUP
    aggregate). No app-count limits configured => empty list => the
    ledger's reserve is one dict probe."""
    if leaf is None:
        return []
    amount = ((APP_SLOT_KEY, 1),)
    out = []
    for q in leaf.ancestors_and_self():
        if q.config.max_applications:
            out.append((f"appq|{q.full_name}",
                        ((APP_SLOT_KEY, int(q.config.max_applications)),),
                        amount))
        for i, lim in enumerate(q.config.limits):
            if lim.max_applications <= 0:
                continue
            lim_items = ((APP_SLOT_KEY, int(lim.max_applications)),)
            if "*" in lim.users or user in lim.users:
                out.append((f"appu|{q.full_name}|{i}|{user}", lim_items,
                            amount))
            for g in groups:
                if g in lim.groups or "*" in lim.groups:
                    out.append((f"appg|{q.full_name}|{i}|{g}", lim_items,
                                amount))
    return out


def legacy_admit(by_queue: Dict[str, list], meta: Dict[str, tuple],
                 queue_tree, seed_admissions=None) -> Tuple[list, int]:
    """The reference-shaped per-ask admission loop: per-queue sorts, per-ask
    quota-chain walks, per-ask user/group-limit checks, per-admission
    accumulator folds. O(pods x chain depth) of host Python — kept as the
    semantic authority the vectorized gate is pinned against (the verify
    mode's oracle) and as the fallback for GateFallback cycles.

    Same contract as vector_admit: by_queue maps qname -> [(app, ask)] with
    exclude_keys already applied, meta maps qname -> (leaf, fair_share,
    priority_adjustment). Returns (admitted asks in global order, held count).
    """
    queue_shares = []
    for qname in by_queue:
        _leaf, share, adj = meta[qname]
        best_prio = max(((e[1].priority or 0) + adj) for e in by_queue[qname])
        # cross-queue: highest adjusted priority first, then fair share
        queue_shares.append((-best_prio, share, qname))
    queue_shares.sort()

    admitted: list = []
    held = 0
    # in-cycle admissions accumulate per queue NODE (keyed by full name) so
    # sibling leaves cannot jointly blow through a shared parent's max
    cycle_extra: Dict[str, Resource] = {}
    # user/group-limit overlay shared across ALL leaves this cycle (keys
    # "<queue>|u|<user>" / "<queue>|g|<group>"), so sibling leaves under a
    # limited parent are jointly capped
    limit_cycle_extra: Dict[str, Resource] = {}
    any_limits = queue_tree.any_limits()
    if seed_admissions:
        for qname, res, user, groups in seed_admissions:
            leaf = queue_tree.resolve(qname, create=False)
            if leaf is None:
                continue
            for q in leaf.ancestors_and_self():
                if q.config.max_resource is not None:
                    cycle_extra[q.full_name] = cycle_extra.get(
                        q.full_name, Resource()).add(res)
            if any_limits and leaf.has_limits_in_chain():
                leaf.record_cycle_admission(user, list(groups), res,
                                            limit_cycle_extra)
    for _neg_prio, _share, qname in queue_shares:
        leaf, _share2, prio_adj = meta[qname]
        entries = by_queue[qname]
        entries.sort(key=lambda e: (
            -((e[1].priority or 0) + prio_adj),
            e[0].submit_time,
            e[1].seq,
        ))
        # queues with no max anywhere in their chain skip the walk entirely
        quota_chain = (
            [q for q in leaf.ancestors_and_self() if q.config.max_resource is not None]
            if leaf is not None else []
        )
        has_limits = (any_limits and leaf is not None
                      and leaf.has_limits_in_chain())
        for app, ask in entries:
            if quota_chain and not fits_quota_with(quota_chain, cycle_extra,
                                                   ask.resource):
                held += 1
                continue
            if has_limits:
                groups = list(app.user.groups)
                if not leaf.fits_user_limit(app.user.user, groups, ask.resource,
                                            cycle_extra=limit_cycle_extra):
                    held += 1
                    continue
                leaf.record_cycle_admission(app.user.user, groups, ask.resource,
                                            limit_cycle_extra)
            for q in quota_chain:
                cycle_extra[q.full_name] = cycle_extra.get(
                    q.full_name, Resource()).add(ask.resource)
            admitted.append(ask)
    return admitted, held


def _check_magnitude(value: int, cap: int = _MAX_BUDGET) -> int:
    if value > cap or value < -cap:
        raise GateFallback(f"quantity {value} exceeds the exact int64 range")
    return value


class _Trackers:
    """Constraint registry: one row per quota node / (queue,user) limit /
    (queue,group) limit, with budgets kept as exact Python ints until the
    matrix is materialized."""

    def __init__(self):
        self.ids: Dict[tuple, int] = {}
        self.budgets: List[Dict[str, int]] = []   # finite components only
        self.res_names: Dict[str, int] = {}       # name -> column

    def _intern(self, key: tuple, budget: Dict[str, int]) -> int:
        tid = self.ids.get(key)
        if tid is None:
            tid = self.ids[key] = len(self.budgets)
            for name, v in budget.items():
                _check_magnitude(v)
                self.res_names.setdefault(name, len(self.res_names))
            self.budgets.append(budget)
        return tid

    def quota(self, q) -> int:
        """Tracker for one queue node with a configured max."""
        key = ("q", q.full_name)
        tid = self.ids.get(key)
        if tid is not None:
            return tid
        mx = q.config.max_resource.resources
        alloc = q.allocated.resources
        return self._intern(key, {k: v - alloc.get(k, 0) for k, v in mx.items()})

    def user_limit(self, q, user: str) -> Optional[int]:
        """Tracker for (queue node, user) — None when no limit at this queue
        applies to the user (recording there could never constrain)."""
        key = ("u", q.full_name, user)
        tid = self.ids.get(key)
        if tid is not None:
            return tid
        budget: Optional[Dict[str, int]] = None
        used = q.user_allocated.get(user)
        used_r = used.resources if used is not None else {}
        for lim in q.config.limits:
            if lim.max_resources is None:
                continue
            if "*" in lim.users or user in lim.users:
                budget = _min_budget(budget, lim.max_resources.resources, used_r)
        if budget is None:
            return None
        return self._intern(key, budget)

    def group_limit(self, q, group: str) -> Optional[int]:
        key = ("g", q.full_name, group)
        tid = self.ids.get(key)
        if tid is not None:
            return tid
        budget: Optional[Dict[str, int]] = None
        used = q.group_allocated.get(group)
        used_r = used.resources if used is not None else {}
        for lim in q.config.limits:
            if lim.max_resources is None:
                continue
            if group in lim.groups or "*" in lim.groups:
                budget = _min_budget(budget, lim.max_resources.resources, used_r)
        if budget is None:
            return None
        return self._intern(key, budget)

    def matrix(self) -> np.ndarray:
        T, K = len(self.budgets), len(self.res_names)
        B = np.full((T, max(K, 1)), _INF, np.int64)
        for t, budget in enumerate(self.budgets):
            for name, v in budget.items():
                B[t, self.res_names[name]] = v
        return B

    def charge(self, key: tuple, res: Resource, B: np.ndarray) -> None:
        """Subtract a seed admission from a tracker's budget row (the
        in-flight batch's conservative quota charge)."""
        tid = self.ids.get(key)
        if tid is None:
            return
        for name, v in res.resources.items():
            col = self.res_names.get(name)
            if col is not None:
                B[tid, col] -= _check_magnitude(v, _MAX_REQ)


def _min_budget(cur: Optional[Dict[str, int]], mx: Dict[str, int],
                used: Dict[str, int]) -> Dict[str, int]:
    """Componentwise-min fold of one applicable limit into the budget:
    several limits on one queue can apply to the same user/group, and the
    shared in-cycle usage tracker must satisfy all of them."""
    out = dict(cur) if cur is not None else {}
    for k, v in mx.items():
        cand = v - used.get(k, 0)
        out[k] = cand if k not in out else min(out[k], cand)
    return out


@dataclasses.dataclass
class GateProblem:
    """One cycle's admission decision, extracted into arrays.

    The shared front end of the two scan back ends (the host numpy scan and
    ops/gate_solve.py's jitted device scan): rank order, exact int64 budget
    matrix, per-ask request rows over the tracked resource columns, and the
    (tracker, ask, weight) membership rows sorted by (tracker, position).
    Extraction is the only phase that touches Python scheduler objects; a
    scan back end consumes arrays only, so the two can be tier-laddered by
    the supervisor without re-walking the queue tree.
    """
    asks_ord: List                 # asks in the legacy global rank order
    n: int                         # len(asks_ord)
    status0: "np.ndarray"          # [n] int8 seed: 1 = tracker-less pre-admit
    Rm: "np.ndarray"               # [n, K] int64 request rows (tracked cols)
    B: "np.ndarray"                # [T, K] int64 budgets (_INF unconstrained)
    mem_tr: "np.ndarray"           # [M] membership tracker id (sorted major)
    mem_pos: "np.ndarray"          # [M] membership ask position (sorted minor)
    mem_w: "np.ndarray"            # [M] legacy charge multiplicity
    T: int                         # tracker count (0 = pure ranking)
    K: int                         # tracked resource column count
    t0: float = 0.0                # extraction start (perf_counter)
    t_rank: float = 0.0            # rank phase end
    t_extract: float = 0.0         # extraction end


class AskExtractCache:
    """Ask-level extraction cache: the per-ask Python derivation inside
    extract_problem's flatten — attribute walks plus the resource-signature
    tuple build — cached across cycles keyed by allocation key and
    validated by ask object identity. The flatten was the last O(pending
    asks) host pass per cycle (ROADMAP round-11 follow-up); with the cache
    a churn cycle re-derives only the asks that actually changed, the same
    O(changed) contract the encoder's row cache and the DeviceRowStore
    already honor. The rank lexsort itself stays O(n log n) in C.

    Validation covers the in-place mutations the scheduler actually
    performs on a reused ask object (update_allocation restamps `seq`; a
    resubmission may swap `resource`): an entry is fresh only when the ask
    object, its resource object, its seq AND its priority all still match —
    anything else re-derives, so a stale signature can never rank or
    charge an ask differently than the legacy loop's fresh attribute reads.

    hits/derived are per-call counters (reset at each extract_problem) so
    the churn test and the cycle entry can pin the contract."""

    def __init__(self):
        # key -> (ask, resource, prio, submit, seq, sig)
        self.d: Dict[str, tuple] = {}
        self.hits = 0
        self.derived = 0


@contextlib.contextmanager
def paused_gc():
    """Cyclic GC paused (restored on exit): the gate's flatten/extract
    phase allocates ~10 tuples+lists per ask, and the collections those
    trigger traverse the scheduler's whole object graph — measured at up to
    a third of the gate's wall time at 50k asks, all jitter."""
    import gc

    was_enabled = gc.isenabled()
    if was_enabled:
        gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()


def vector_admit(by_queue: Dict[str, list], meta: Dict[str, tuple],
                 queue_tree, seed_admissions=None,
                 cache=None) -> Tuple[list, int, dict]:
    """Array-form replacement for the legacy gate's rank + admit phases:
    extract_problem + the host numpy scan (host_scan), GC paused."""
    with paused_gc():
        return host_scan(
            extract_problem(by_queue, meta, queue_tree, seed_admissions,
                            cache=cache))


def extract_problem(by_queue, meta, queue_tree,
                    seed_admissions=None, cache=None) -> GateProblem:
    """Flatten pending asks into a GateProblem — see GateProblem.

    by_queue: qname -> [(app, ask)] pending entries (exclude_keys already
    applied by the collector). meta: qname -> (leaf, fair_share, prio_adj)
    resolved by the caller (per-cycle cached). queue_tree: the live
    QueueTree (seed charging resolves queues the pending set may not name).
    cache: optional AskExtractCache — per-ask derivation (priority/submit/
    seq attribute walks + the resource-signature tuple) then runs only for
    asks not seen before or replaced since (identity-validated), so a churn
    cycle's flatten is O(changed asks) of Python plus C-level array work.

    Raises GateFallback when the cycle cannot be represented exactly.
    """
    t0 = time.perf_counter()
    qnames = list(by_queue)
    if not qnames:
        return GateProblem(asks_ord=[], n=0, status0=np.empty(0, np.int8),
                           Rm=np.empty((0, 1), np.int64),
                           B=np.empty((0, 1), np.int64),
                           mem_tr=np.empty(0, np.int64),
                           mem_pos=np.empty(0, np.int64),
                           mem_w=np.empty(0, np.int64),
                           T=0, K=1, t0=t0, t_rank=t0, t_extract=t0)
    if sum(len(v) for v in by_queue.values()) > _MAX_ASKS:
        raise GateFallback(
            f"batch exceeds the exact-arithmetic ceiling of {_MAX_ASKS} asks")

    # ---- per-queue extraction + queue order
    # zip-unpack + C-level attrgetter maps (measurably faster than per-entry
    # Python loops or scalar stores into numpy arrays); queue order is the
    # legacy (-best_adjusted_prio, share, name) tuple sort.
    from operator import attrgetter

    get_prio = attrgetter("priority")
    get_submit = attrgetter("submit_time")
    get_seq = attrgetter("seq")
    if cache is not None:
        cache.hits = cache.derived = 0
    q_data = []
    for qname in qnames:
        entries_q = by_queue[qname]
        _leaf, share, adj = meta[qname]
        apps_q, asks_q = zip(*entries_q)
        if cache is not None:
            # ask-level cache: derive only entries whose ask object changed
            getd = cache.d.get
            prio_l: List[int] = []
            submit_l: List[float] = []
            seq_l: List[int] = []
            sig_q: List[tuple] = []
            for app, ask in entries_q:
                e = getd(ask.allocation_key)
                if (e is None or e[0] is not ask
                        or e[1] is not ask.resource or e[4] != ask.seq
                        or e[2] != (ask.priority or 0)):
                    e = (ask, ask.resource, int(ask.priority or 0),
                         app.submit_time, ask.seq, tuple(_res_items(ask)))
                    cache.d[ask.allocation_key] = e
                    cache.derived += 1
                else:
                    cache.hits += 1
                prio_l.append(e[2])
                submit_l.append(e[3])
                seq_l.append(e[4])
                sig_q.append(e[5])
            prio = np.asarray(prio_l, np.int64) + adj
            submit = np.asarray(submit_l, np.float64)
            seq = np.asarray(seq_l, np.int64)
        else:
            sig_q = None
            prio_raw = list(map(get_prio, asks_q))
            try:
                prio = np.asarray(prio_raw, np.int64) + adj
            except (TypeError, ValueError):
                # defensive None-priority path (ask.priority or 0)
                prio = np.asarray([(p or 0) for p in prio_raw],
                                  np.int64) + adj
            submit = np.asarray(list(map(get_submit, apps_q)), np.float64)
            seq = np.asarray(list(map(get_seq, asks_q)), np.int64)
        q_data.append((-int(prio.max()), share, qname, prio, submit, seq,
                       apps_q, asks_q, sig_q))
    q_data.sort(key=lambda t: t[:3])
    if (cache is not None
            and len(cache.d) > 2 * sum(len(t[7]) for t in q_data) + 1024):
        # keys consumed through other paths leave orphans; sweep rarely
        live = {a.allocation_key for t in q_data for a in t[7]}
        cache.d = {k: v for k, v in cache.d.items() if k in live}

    # ---- flatten in queue order + global rank (one lexsort; stable, like
    # the legacy stable per-queue sort with its (prio, submit, seq) key)
    asks_flat: List = []
    for t in q_data:
        asks_flat += t[7]
    n = len(asks_flat)
    counts = np.asarray([len(t[7]) for t in q_data], np.int64)
    a_qord = np.repeat(np.arange(len(q_data), dtype=np.int64), counts)
    a_negprio = -np.concatenate([t[3] for t in q_data])
    a_submit = np.concatenate([t[4] for t in q_data])
    a_seq = np.concatenate([t[5] for t in q_data])
    order = np.lexsort((a_seq, a_submit, a_negprio, a_qord))
    asks_ord = [asks_flat[i] for i in order.tolist()]
    t_rank = time.perf_counter()

    # ---- constraint trackers
    # Each ask carries a (tracker ids, weights) combo: ids are UNIQUE per
    # ask, the weight is how many times the legacy loop would charge that
    # tracker per admission (a duplicated group in the user's group list
    # double-charges the shared group accumulator — the feasibility CHECK
    # still uses the request once, which is why checks below use the
    # exclusive prefix plus a single request row rather than the weighted
    # inclusive prefix). Combos are resolved once per APPLICATION (every
    # ask of an app shares queue + user, and apps are orders of magnitude
    # fewer than asks), then broadcast to entries with C-level id() maps
    # and reordered into rank order by numpy.
    trackers = _Trackers()
    any_limits = queue_tree.any_limits()
    combos: List[Tuple[tuple, tuple]] = []   # combo id -> (ids, wts)
    combo_key: Dict[tuple, int] = {}
    app_combo: Dict[int, int] = {}           # id(app) -> combo id (-1 = none)
    # (qname, user, groups) -> (ids, weights) for the limit trackers
    lim_tr: Dict[tuple, tuple] = {}
    combo_flat: List[int] = []
    for t in q_data:
        qname = t[2]
        leaf = meta[qname][0]
        apps_q = t[6]
        if leaf is None:
            combo_flat += [-1] * len(apps_q)
            continue
        chain = leaf.ancestors_and_self()
        quota_ids = tuple(trackers.quota(q) for q in chain
                          if q.config.max_resource is not None)
        has_limits = any_limits and leaf.has_limits_in_chain()
        for app in {id(a): a for a in apps_q}.values():
            if id(app) in app_combo:
                continue
            ids: tuple = quota_ids
            wts: tuple = (1,) * len(quota_ids)
            if has_limits:
                lkey = (qname, app.user.user, tuple(app.user.groups))
                lw = lim_tr.get(lkey)
                if lw is None:
                    lcounts: Dict[int, int] = {}
                    for q in chain:
                        if not q.config.limits:
                            continue
                        tid = trackers.user_limit(q, app.user.user)
                        if tid is not None:
                            lcounts[tid] = lcounts.get(tid, 0) + 1
                        for g in app.user.groups:
                            tid = trackers.group_limit(q, g)
                            if tid is not None:
                                lcounts[tid] = lcounts.get(tid, 0) + 1
                    lw = lim_tr[lkey] = (tuple(lcounts),
                                         tuple(lcounts.values()))
                ids = ids + lw[0]
                wts = wts + lw[1]
            if ids:
                ck = (ids, wts)
                c = combo_key.get(ck)
                if c is None:
                    c = combo_key[ck] = len(combos)
                    combos.append(ck)
            else:
                c = -1
            app_combo[id(app)] = c
        combo_flat += list(map(app_combo.__getitem__, map(id, apps_q)))

    T = len(trackers.budgets)
    if T == 0:
        # no quota, no limits anywhere near the pending set: pure ranking
        return GateProblem(asks_ord=asks_ord, n=n,
                           status0=np.ones((n,), np.int8),
                           Rm=np.empty((0, 1), np.int64),
                           B=np.empty((0, 1), np.int64),
                           mem_tr=np.empty(0, np.int64),
                           mem_pos=np.empty(0, np.int64),
                           mem_w=np.empty(0, np.int64),
                           T=0, K=1, t0=t0, t_rank=t_rank,
                           t_extract=time.perf_counter())

    B = trackers.matrix()
    K = B.shape[1]

    # seed admissions (the pipelined in-flight batch) charge budgets exactly
    # like the legacy pre-populated cycle_extra accumulators
    if seed_admissions:
        for qname, res, user, groups in seed_admissions:
            leaf = queue_tree.resolve(qname, create=False)
            if leaf is None:
                continue
            for q in leaf.ancestors_and_self():
                if q.config.max_resource is not None:
                    trackers.charge(("q", q.full_name), res, B)
            if any_limits and leaf.has_limits_in_chain():
                for q in leaf.ancestors_and_self():
                    if not q.config.limits:
                        continue
                    trackers.charge(("u", q.full_name, user), res, B)
                    for g in groups:
                        trackers.charge(("g", q.full_name, g), res, B)

    # ---- request rows over the tracked resource columns, deduped by
    # shape. The signature is the raw insertion-order item tuple (dedup is
    # purely a throughput optimization); rows are built once per distinct
    # shape and broadcast with one fancy-index gather. Unconstrained asks
    # get rows too — harmless, they have no membership entries.
    if cache is not None:
        sig_flat: List[tuple] = []
        for t in q_data:
            sig_flat += t[8]
        sigs = [sig_flat[i] for i in order.tolist()]
    else:
        sigs = list(map(tuple, map(_res_items, asks_ord)))
    names = trackers.res_names
    row_gid: Dict[tuple, int] = {}
    rows_l: List[np.ndarray] = []
    for sig in set(sigs):
        row = np.zeros((K,), np.int64)
        for name, v in sig:
            if v < 0:
                raise GateFallback(f"negative request component {name}={v}")
            col = names.get(name)
            if col is not None:
                row[col] = _check_magnitude(v, _MAX_REQ)
        row_gid[sig] = len(rows_l)
        rows_l.append(row)
    gid_arr = np.fromiter(map(row_gid.__getitem__, sigs), np.int64, count=n)
    Rm = np.stack(rows_l)[gid_arr]

    # per-ask combo ids reordered from flat (queue-major) into rank order
    combo_arr = np.asarray(combo_flat, np.int64)[order]

    # ---- membership rows (unique (tracker, ask) pairs), sorted by
    # (tracker, position); mem_w carries the legacy charge multiplicity.
    # Expanded combo-wise with repeat/tile + one lexsort — no Python loop
    # over (ask x tracker) pairs.
    by_combo = np.argsort(combo_arr, kind="stable")
    bounds = np.searchsorted(combo_arr[by_combo], np.arange(len(combos) + 1))
    chunks_tr, chunks_pos, chunks_w = [], [], []
    for c, (ids, wts) in enumerate(combos):
        positions = by_combo[bounds[c]:bounds[c + 1]]
        if positions.size == 0:
            continue
        chunks_pos.append(np.repeat(positions, len(ids)))
        chunks_tr.append(np.tile(np.asarray(ids, np.int64), positions.size))
        chunks_w.append(np.tile(np.asarray(wts, np.int64), positions.size))
    if chunks_tr:
        mem_tr = np.concatenate(chunks_tr)
        mem_pos = np.concatenate(chunks_pos)
        mem_w = np.concatenate(chunks_w)
        morder = np.lexsort((mem_pos, mem_tr))
        mem_tr, mem_pos, mem_w = mem_tr[morder], mem_pos[morder], mem_w[morder]
        # the module-top caps bound the weight-1 cumulative sum at
        # n x _MAX_REQ <= 2^60; duplicated-group charge weights multiply
        # every membership row, so the weighted worst case is
        # w_max x n x _MAX_REQ — re-check it against the same ceiling
        # (w_max x n in place of n) so cs + pre can neither trip an
        # unconstrained _INF column nor wrap int64
        w_max = int(mem_w.max())
        if w_max > 1 and w_max * n > _MAX_ASKS:
            raise GateFallback(
                f"weighted charge bound {w_max}x{n} exceeds the "
                f"exact-arithmetic ceiling of {_MAX_ASKS}")
    else:
        mem_tr = mem_pos = mem_w = np.empty(0, np.int64)

    status0 = np.zeros((n,), np.int8)   # 0 undecided, 1 admitted, -1 held
    status0[combo_arr < 0] = 1          # tracker-less asks always admit
    return GateProblem(asks_ord=asks_ord, n=n, status0=status0, Rm=Rm, B=B,
                       mem_tr=mem_tr, mem_pos=mem_pos, mem_w=mem_w,
                       T=T, K=K, t0=t0, t_rank=t_rank,
                       t_extract=time.perf_counter())


def _segments(mt: "np.ndarray") -> Tuple["np.ndarray", "np.ndarray"]:
    """(seg_start, seg_of) for tracker-major membership rows: the first row
    index of each tracker segment, and each row's segment ordinal."""
    seg_start = np.flatnonzero(np.r_[True, mt[1:] != mt[:-1]])
    seg_len = np.diff(np.r_[seg_start, mt.size])
    return seg_start, np.repeat(np.arange(seg_start.size), seg_len)


def _seg_excl_cumsum(X: "np.ndarray", seg_start, seg_of) -> "np.ndarray":
    """Segmented EXCLUSIVE cumsum of [M, K] rows, in place on a fresh
    array: cs becomes sum of the rows strictly before each row within its
    segment (segment 0 always starts at row 0, so only its offset needs
    zeroing). Callers may keep mutating the returned array."""
    cs = np.cumsum(X, axis=0)
    offset = cs[np.maximum(seg_start - 1, 0)]
    offset[0] = 0
    cs -= offset[seg_of]
    cs -= X
    return cs


def host_scan(problem: GateProblem) -> Tuple[list, int, dict]:
    """The host numpy scan back end: iterative one-sided-overestimate passes
    over a GateProblem, compacting the membership arrays between passes.
    Returns (admitted asks in global order, held count, stats)."""
    n, T = problem.n, problem.T
    if n == 0:
        return [], 0, {"path": "vector", "passes": 0, "trackers": 0}
    asks_ord = problem.asks_ord
    t0, t_rank = problem.t0, problem.t_rank
    if T == 0:
        return (asks_ord, 0,
                {"path": "vector", "passes": 0, "trackers": 0,
                 "rank_ms": (t_rank - t0) * 1000,
                 "admit_ms": (time.perf_counter() - t_rank) * 1000})
    Rm, B, K = problem.Rm, problem.B, problem.K
    mem_tr, mem_pos, mem_w = problem.mem_tr, problem.mem_pos, problem.mem_w

    # ---- iterative vectorized admission
    status = problem.status0.copy()
    # live membership view, compacted to undecided rows between passes: pass
    # 1 touches everything, later passes only the deferred remainder. `pre`
    # carries, per surviving row, the EXACT weighted usage of the already-
    # admitted asks BEFORE that row in that tracker — the sequential loop's
    # accumulator state baked per row, so admitting an ask that comes after
    # a deferred one can never pollute the deferred ask's prefix.
    mt, mp, mw = mem_tr, mem_pos, mem_w
    pre = np.zeros((mt.size, K), np.int64)
    # per-row gathers carried across compaction (re-gathering Rm[mp]/B[mt]
    # every pass was a third of the admit cost on saturated traces)
    rrow = Rm[mp]                       # single request row per membership
    req = rrow * mw[:, None]            # weighted charge per membership
    bm = B[mt]                          # budget row per membership
    passes = 0
    while mt.size and passes < _MAX_PASSES:
        passes += 1
        # weighted rows feed the running usage (charge semantics); the
        # feasibility check is pre + undecided-exclusive-prefix + a SINGLE
        # request row — an over-estimate of the legacy "usage so far + r
        # within limit" test (every undecided predecessor counted, a
        # superset of the truly-admitted ones), and one-sided: passing it
        # proves the exact check passes
        seg_start, seg_of = _segments(mt)
        # in-place: cs is the exclusive prefix, then the full check sum
        cs = _seg_excl_cumsum(req, seg_start, seg_of)
        cs += pre
        cs += rrow
        row_viol = (cs > bm).any(axis=1)
        if not row_viol.any():
            status[mp] = 1
            break
        # ask-level violator: violates in ANY of its trackers
        ask_viol = np.bincount(mp[row_viol], minlength=n).astype(bool)
        viol_rows = ask_viol[mp]
        # every non-violator admits (the one-sided over-estimate)
        adm_rows = ~viol_rows
        status[mp[adm_rows]] = 1
        # a violator holds iff NO earlier violator shares any tracker: its
        # undecided predecessors are then all non-violators — all admitted
        # this pass — so its prefix is exact and the violation is real.
        # Otherwise the earlier violator's removal could free budget: defer.
        vpos = np.where(viol_rows, mp, n)
        first_viol = np.minimum.reduceat(vpos, seg_start)
        blocked = np.bincount(mp[first_viol[seg_of] < mp], minlength=n) > 0
        status[np.flatnonzero(ask_viol & ~blocked)] = -1
        # bake this pass's admissions into the surviving rows' prefixes:
        # segmented exclusive cumsum over admitted rows only (a deferred
        # row's own contribution is zero, so inclusive == exclusive there)
        pre = pre + _seg_excl_cumsum(req * adm_rows[:, None],
                                     seg_start, seg_of)
        # definite-hold sweep over the deferred remainder: admitted usage
        # before a row only grows across passes, so an ask whose own
        # request no longer fits on some tracker can never admit
        und = status[mp] == 0
        if und.any():
            solo = (pre[und] + rrow[und] > bm[und]).any(axis=1)
            if solo.any():
                status[mp[und][solo]] = -1
        und = status[mp] == 0
        mt, mp, mw = mt[und], mp[und], mw[und]
        pre, rrow, req, bm = pre[und], rrow[und], req[und], bm[und]

    # pathological non-convergence: exact per-ask finish over the leftovers
    finish = exact_finish(problem, status, mt, mp, mw, pre)

    admitted = [asks_ord[pos] for pos in np.flatnonzero(status == 1).tolist()]
    held = int((status == -1).sum())
    t_end = time.perf_counter()
    return admitted, held, {
        "path": "vector", "passes": passes, "trackers": T,
        "finish_loop": finish,
        "rank_ms": (t_rank - t0) * 1000,
        "admit_ms": (t_end - t_rank) * 1000,
    }


def exact_finish(problem: GateProblem, status, mt, mp, mw, pre) -> int:
    """Exact per-ask finish over the undecided leftovers, in ask order.

    mt/mp/mw are the COMPACTED membership rows still live (undecided asks
    only, tracker-major), `pre` their admitted-predecessor usage — the
    sequential loop's accumulator state baked per row. `extra` accumulates
    usage admitted DURING this finish per tracker — together they ARE the
    legacy accumulators. Mutates `status` in place; returns the number of
    asks finished this way (0 on the common converged case).
    """
    finish = np.flatnonzero(status == 0)
    if finish.size:
        Rm, B = problem.Rm, problem.B
        extra = np.zeros((problem.T, problem.K), np.int64)
        for pos in finish.tolist():
            rows_i = np.flatnonzero(mp == pos)
            tl = mt[rows_i]
            row = Rm[pos]
            if ((pre[rows_i] + extra[tl] + row) > B[tl]).any():
                status[pos] = -1
            else:
                np.add.at(extra, tl, row[None, :] * mw[rows_i][:, None])
                status[pos] = 1
    return int(finish.size)


def finish_leftovers(problem: GateProblem, status) -> int:
    """Exact finish for a scan that returned undecided leftovers WITHOUT the
    compacted prefix state (the device scan's bounded-pass cap overflow):
    rebuild each undecided row's admitted-predecessor usage with one
    segmented pass over the full membership arrays, then run exact_finish.
    O(M·K) once plus O(leftovers) — leftovers are rare by construction.
    Mutates `status` in place; returns the finished-ask count."""
    if not (status == 0).any():
        return 0
    mt, mp, mw = problem.mem_tr, problem.mem_pos, problem.mem_w
    reqw = problem.Rm[mp] * mw[:, None]
    # admitted usage strictly BEFORE each row, within its tracker segment
    pre = _seg_excl_cumsum(reqw * (status[mp] == 1)[:, None],
                           *_segments(mt))
    und = status[mp] == 0
    return exact_finish(problem, status, mt[und], mp[und], mw[und], pre[und])
