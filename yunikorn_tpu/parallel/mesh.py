"""Multi-chip scaling: shard the solve over a device mesh.

The reference scales by raising QPS against the K8s API and by the core's
single-threaded cycle (SURVEY.md §2.5: no distributed backend exists or is
needed there). The TPU-native scale-out story is different: the pods×nodes
feasibility/scoring problem shards over the NODE dimension the way sequence
parallelism shards sequence (SURVEY.md §5 "long-context" note):

  mesh: 1-D ("nodes",) over all chips (ICI within a slice, DCN across)
  node-side arrays  [M, ...]  → sharded along M   (PartitionSpec("nodes"))
  pod-side arrays   [N, ...]  → replicated        (small: one row per pod)
  group feasibility [G, M]    → sharded along M

Under jit+GSPMD each chip evaluates predicates/fit/scoring for its node shard;
the per-pod argmax over M becomes a sharded reduce (XLA inserts the ICI
all-reduce); the water-fill and accept stages run on the replicated [N] data.
Assignment extraction gathers one int32 per pod.

This module provides the mesh construction + sharded wrapper around
ops.assign.solve. It works on any device set — the test/dryrun path uses a
virtual 8-device CPU mesh.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from yunikorn_tpu.ops import assign as assign_mod

NODE_AXIS = "nodes"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def _shardings(mesh: Mesh):
    node_sharded = NamedSharding(mesh, P(NODE_AXIS))
    node_sharded2 = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())
    return node_sharded, node_sharded2, repl


def solve_sharded(batch, node_arrays, mesh: Mesh, *, max_rounds: int = 16,
                  chunk: int = 512, policy: str = "binpacking",
                  free_delta=None) -> assign_mod.SolveResult:
    """Like ops.assign.solve_batch but with node-dimension sharding over mesh.

    M must be divisible by the mesh size (NodeArrays capacities are powers of
    two, meshes are 2^k chips, so this holds by construction).
    """
    na = node_arrays
    n_dev = mesh.devices.size
    M = na.capacity
    assert M % n_dev == 0, f"node capacity {M} not divisible by mesh size {n_dev}"
    node_s, node_s2, repl = _shardings(mesh)

    free_i = np.floor(na.free).astype(np.int32)
    if free_delta is not None:
        d = np.zeros_like(free_i)
        rows = min(free_i.shape[0], free_delta.shape[0])
        cols = min(free_i.shape[1], free_delta.shape[1])
        d[:rows, :cols] = np.ceil(free_delta[:rows, :cols]).astype(np.int32)
        free_i = free_i - d
    node_ok = na.valid & na.schedulable

    put = jax.device_put
    args = (
        put(batch.req.astype(np.int32), repl),
        put(batch.group_id, repl),
        put(batch.rank, repl),
        put(batch.valid, repl),
        put(batch.g_term_req, repl),
        put(batch.g_term_forb, repl),
        put(batch.g_term_valid, repl),
        put(batch.g_anyof, repl),
        put(batch.g_anyof_valid, repl),
        put(batch.g_tol, repl),
        put(batch.g_ports, repl),
        put(batch.g_pref_req, repl),
        put(batch.g_pref_forb, repl),
        put(batch.g_pref_weight, repl),
        put(na.labels, node_s2),
        put(na.taints_hard, node_s2),
        put(na.taints_soft, node_s2),
        put(na.ports, node_s2),
        put(node_ok, node_s),
        put(free_i, node_s2),
        put(np.floor(na.capacity_arr).astype(np.int32), node_s2),
    )
    group_node_s = NamedSharding(mesh, P(None, NODE_AXIS))
    host_mask = batch.g_host_mask
    mask_arg = (put(assign_mod.pad2d(host_mask, M, False), group_node_s)
                if host_mask is not None else None)
    host_soft = getattr(batch, "g_host_soft", None)
    soft_arg = (put(assign_mod.pad2d(host_soft, M, np.float32(0.0)), group_node_s)
                if host_soft is not None else None)

    loc_arg = None
    if batch.locality is not None:
        lb = batch.locality
        # locality tables ride replicated: tiny relative to the node arrays,
        # and the per-round count updates are global reductions anyway
        loc_arg = tuple(
            put(a, repl) for a in (lb.dom, lb.cnt0, lb.dom_valid, lb.contrib,
                                   lb.g_refs, lb.g_kind, lb.g_skew, lb.g_seed,
                                   lb.g_weight)
        )

    with mesh:
        assigned, free_after, rounds = assign_mod.solve(
            *args, mask_arg, soft_arg, loc_arg,
            max_rounds=max_rounds, chunk=min(chunk, batch.req.shape[0]),
            policy=policy,
            has_loc_soft=(batch.locality is not None
                          and bool(np.any(batch.locality.g_weight))),
        )
    return assign_mod.SolveResult(assigned=assigned, free_after=free_after, rounds=rounds)
