"""Multi-chip scaling: shard the solve over a device mesh.

The reference scales by raising QPS against the K8s API and by the core's
single-threaded cycle (SURVEY.md §2.5: no distributed backend exists or is
needed there). The TPU-native scale-out story is different: the pods×nodes
feasibility/scoring problem shards over the NODE dimension the way sequence
parallelism shards sequence (SURVEY.md §5 "long-context" note):

  mesh: 1-D ("nodes",) over all chips (ICI within a slice, DCN across)
  node-side arrays  [M, ...]  → sharded along M   (PartitionSpec("nodes"))
  pod-side arrays   [N, ...]  → replicated        (small: one row per pod)
  group feasibility [G, M]    → sharded along M

Under jit+GSPMD each chip evaluates predicates/fit/scoring for its node shard;
the per-pod argmax over M becomes a sharded reduce (XLA inserts the ICI
all-reduce); the water-fill and accept stages run on the replicated [N] data.
Assignment extraction gathers one int32 per pod.

This module provides the mesh construction + sharded wrapper around
ops.assign.solve. It works on any device set — the test/dryrun path uses a
virtual 8-device CPU mesh.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from yunikorn_tpu.ops import assign as assign_mod

NODE_AXIS = "nodes"

# Pack under a mesh (solver.policy=optimal + shardSolve): supported since
# the mesh-aligned partitioner landed (round 15) — `pack_solve_sharded`
# below dispatches ops/pack_solve with partitioner="topo", which orders
# nodes by (GSPMD shard, ICI domain, row) and cuts parts on shard
# boundaries, so every part's dense relaxation state is chip-local under
# the static node sharding instead of fighting it the way POP's random
# node permutation did. Differential parity vs the single-shard solve on
# the same trace is pinned by tests/test_topology.py.
PACK_SHARDED_SUPPORTED = True

# Learned policy under a mesh (solver.policy=learned + shardSolve):
# supported since round 19 — the two-tower params ride `solve_sharded`'s
# learned tail replicated (tiny pytree; the per-round node-tower re-embed
# is an [M_shard, F] matmul per chip, node-dim local), so sharded cycles
# score instead of silently skipping (policy follow-up (c)).
LEARNED_SHARDED_SUPPORTED = True

# Cvx full-fleet arm under a mesh (solver.pack=cvx + shardSolve): the dense
# [N, M] relaxation state shards along M like every node-dim tensor (X,
# feasibility, soft scores all partition on the fleet axis; the row-simplex
# projection's row reductions become ICI all-reduces), `cvx_solve_sharded`
# below. Single-device parity is pinned by tests/test_cvx_solve.py.
CVX_SHARDED_SUPPORTED = True

# Host bytes of the pod-side (replicated) solve args assembled by the LAST
# solve_sharded call. Node-side tensors ride the persistent device mirror
# (DeviceNodeState tracks those uploads); the replicated pod batch re-ships
# every cycle, and at 64k pods that is the sharded path's dominant per-cycle
# transfer — the core folds this into device_transfer_bytes_total and the
# cycle's trace span. Single writer (the scheduler thread owns dispatch).
last_replicated_bytes = 0


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def _shardings(mesh: Mesh):
    node_sharded = NamedSharding(mesh, P(NODE_AXIS))
    node_sharded2 = NamedSharding(mesh, P(NODE_AXIS, None))
    repl = NamedSharding(mesh, P())
    return node_sharded, node_sharded2, repl


def solve_sharded(batch, node_arrays, mesh: Mesh, *, max_rounds: int = 16,
                  chunk: int = 512, policy: str = "binpacking",
                  free_delta=None, node_mask=None, ports_delta=None,
                  compile_only: bool = False,
                  max_batch: int = assign_mod.MAX_SOLVE_PODS,
                  device_state=None, aot_pending: bool = False,
                  learned=None, aot_extra: tuple = (),
                  ) -> Optional[assign_mod.SolveResult]:
    """Like ops.assign.solve_batch but with node-dimension sharding over mesh.

    M must be divisible by the mesh size (NodeArrays capacities are powers of
    two, meshes are 2^k chips, so this holds by construction). Arg assembly
    (dtype views, inflight overlay, partition node_mask, static-variant
    selection) is shared with the single-device path via prepare_solve_args,
    so the production scheduler can route here without semantic drift. The
    sharded program stays on the XLA path (no pallas): pallas_call under
    GSPMD auto-partitioning would need a shard_map wrapper, and the sharded
    argmax-over-M already reduces over ICI.

    learned: the (params pytree, seed) tuple of the two-tower scorer —
    replicated like the pod-side args (the params are KiB-scale; the
    per-round node-tower matmuls stay node-dim local). Pass
    aot_extra=("policy", ckpt_hash) with it so a checkpoint swap can never
    serve a stale stored executable (the solve_batch contract).
    """
    na = node_arrays
    n_dev = mesh.devices.size
    M = na.capacity
    assert M % n_dev == 0, f"node capacity {M} not divisible by mesh size {n_dev}"
    node_s, node_s2, repl = _shardings(mesh)
    group_node_s = NamedSharding(mesh, P(None, NODE_AXIS))

    # device_state: persistent node tensors already committed with this
    # mesh's shardings (SnapshotEncoder.device_arrays(mesh=...)); device_put
    # below then recognizes the matching sharding and skips the transfer, so
    # chunk-invariant node state moves across the ICI once per change, not
    # once per cycle.
    np_args, static_kwargs = assign_mod.prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        # replicated device_put of pod args expects host arrays; the
        # row-store req is a single-device gather the mesh path skips
        allow_req_device=False)

    if not compile_only:
        global last_replicated_bytes
        # pod-side args only (indexes 0..13 of SOLVE_ARG_NAMES order): the
        # node-side tensors either live on device already (device_state) or
        # are counted by DeviceNodeState on their own refresh
        last_replicated_bytes = sum(
            a.nbytes for a in np_args[:14] if hasattr(a, "nbytes"))

    N = np_args[0].shape[0]
    mb = 1 << (max(int(max_batch), 64).bit_length() - 1)

    if compile_only:
        # AOT-lower with sharded input specs (no transfer, no execution):
        # fills the jit + persistent caches with exactly the program the
        # production sharded cycle runs (bucket prewarm). Oversize batches
        # compile the canonical [mb]-pod chunk shape — the only shape the
        # chained production path below ever runs.
        put = lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
    else:
        put = jax.device_put

    def build_args(cargs):
        (req, group_id, rank, valid, g_term_req, g_term_forb, g_term_valid,
         g_anyof, g_anyof_valid, g_tol, g_ports, g_pref_req, g_pref_forb,
         g_pref_weight, labels, taints_hard, taints_soft, ports, node_ok,
         free_i, cap_i, host_mask, host_soft, loc, topo) = cargs
        args = (
            put(req, repl), put(group_id, repl), put(rank, repl), put(valid, repl),
            put(g_term_req, repl), put(g_term_forb, repl), put(g_term_valid, repl),
            put(g_anyof, repl), put(g_anyof_valid, repl),
            put(g_tol, repl), put(g_ports, repl),
            put(g_pref_req, repl), put(g_pref_forb, repl), put(g_pref_weight, repl),
            put(labels, node_s2), put(taints_hard, node_s2),
            put(taints_soft, node_s2), put(ports, node_s2),
            put(node_ok, node_s),
            put(free_i, node_s2),
            put(cap_i, node_s2),
        )
        mask_arg = put(host_mask, group_node_s) if host_mask is not None else None
        soft_arg = put(host_soft, group_node_s) if host_soft is not None else None
        # locality tables ride replicated: tiny relative to the node arrays,
        # and the per-round count updates are global reductions anyway
        loc_arg = (tuple(put(a, repl) for a in loc)
                   if loc is not None else None)
        # topology tuple: node_dom shards with the node dim, the [G']/[D]
        # tables replicate (tiny; the refined-group gather is group-dim)
        topo_arg = None
        if topo is not None:
            topo_arg = (put(topo[0], node_s),) + tuple(
                put(a, repl) for a in topo[1:])
        return args, mask_arg, soft_arg, loc_arg, topo_arg

    # learned tail, replicated (params leaves are tiny; the seed is a
    # traced int32, reseeding never recompiles)
    learned_arg = None
    if learned is not None:
        learned_arg = (
            jax.tree_util.tree_map(lambda a: put(jnp.asarray(a), repl),
                                   learned[0]),
            put(jnp.asarray(learned[1], jnp.int32), repl))

    solve_kwargs = dict(
        max_rounds=max_rounds, chunk=min(chunk, min(N, mb)),
        policy=policy, has_loc_soft=static_kwargs["has_loc_soft"],
        score_cols=static_kwargs["score_cols"],
    )
    from yunikorn_tpu.aot import runtime as aot_rt

    # the mesh tag keeps sharded programs in their own AOT-fingerprint space:
    # a single-device executable and a sharded one can share identical avals
    # (same shapes/dtypes) but are different compiled programs
    aot_extra = ("mesh", n_dev) + tuple(aot_extra)
    if N > mb:
        # one compiled lax.scan program over [mb]-pod rank-ordered slices
        # (assign.solve_chunked) — same sharding layout, group state hoisted
        np_args_s, order = assign_mod._sort_pods_by_rank(np_args)
        args, mask_arg, soft_arg, loc_arg, topo_arg = build_args(np_args_s)
        ck = dict(solve_kwargs, chunk_pods=mb)
        with mesh:
            if compile_only:
                aot_rt.aot_compile(
                    "mesh.solve_chunked", assign_mod.solve_chunked,
                    (*args, mask_arg, soft_arg, loc_arg, topo_arg,
                     learned_arg), ck,
                    extra=aot_extra, lower_cm=mesh)
                return None
            assigned, around, free_after, rounds, _ = aot_rt.aot_call(
                "mesh.solve_chunked", assign_mod.solve_chunked,
                (*args, mask_arg, soft_arg, loc_arg, topo_arg, learned_arg),
                ck, pending_ok=aot_pending, extra=aot_extra, lower_cm=mesh)
        if order is not None:
            assigned, around = assign_mod._unsort(order, assigned, around)
        return assign_mod.SolveResult(
            assigned=assigned, free_after=free_after, rounds=rounds,
            accept_round=around)

    args, mask_arg, soft_arg, loc_arg, topo_arg = build_args(np_args)
    with mesh:
        if compile_only:
            aot_rt.aot_compile(
                "mesh.solve", assign_mod.solve,
                (*args, mask_arg, soft_arg, loc_arg, topo_arg, learned_arg),
                solve_kwargs, extra=aot_extra, lower_cm=mesh)
            return None
        assigned, around, free_after, rounds, _ = aot_rt.aot_call(
            "mesh.solve", assign_mod.solve,
            (*args, mask_arg, soft_arg, loc_arg, topo_arg, learned_arg),
            solve_kwargs, pending_ok=aot_pending, extra=aot_extra,
            lower_cm=mesh)
    return assign_mod.SolveResult(assigned=assigned, free_after=free_after,
                                  rounds=rounds, accept_round=around)


def pack_solve_sharded(batch, node_arrays, mesh: Mesh, *,
                       policy: str = "binpacking", free_delta=None,
                       node_mask=None, ports_delta=None, seed: int = 0,
                       chunk: int = 512, device_state=None,
                       aot_pending: bool = False):
    """Node-dimension sharded dispatch of ops.pack_solve.pack_solve.

    Same layout contract as solve_sharded — pod/group args replicate,
    node-side tensors shard along M — with the partitioner forced to the
    mesh-aligned "topo" mode: `pick_parts(..., n_shards=mesh size)` floors
    the part count at the shard count and the (shard, ICI-domain, row)
    node ordering cuts every part inside one shard, so the partition
    layout composes with the static GSPMD node sharding instead of
    fighting it the way POP's random permutation did. Placement parity vs
    the single-shard program is pinned by tests/test_topology.py (it only
    holds because the solve's free carry is exactly [M, R] — the round-15
    root-cause fix for the uneven-shard dummy-row miscompile, see
    ops/assign._segment_prefix_accept). Raises PackUnsupported when the
    shape cannot split into whole parts per shard."""
    from yunikorn_tpu.ops import pack_solve as pack_mod
    from yunikorn_tpu.ops.assign import SOLVE_ARG_NAMES

    if batch.locality is not None:
        raise pack_mod.PackUnsupported(
            "locality batches take the greedy path")
    if batch.g_ports.view(np.uint32).any():
        raise pack_mod.PackUnsupported(
            "host-port batches take the greedy path")
    n_dev = mesh.devices.size
    np_args, static_kwargs = assign_mod.prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        allow_req_device=False)
    N = np_args[SOLVE_ARG_NAMES.index("req")].shape[0]
    M = np_args[SOLVE_ARG_NAMES.index("free")].shape[0]
    if not pack_mod.shape_supported(N, M, n_shards=n_dev):
        raise pack_mod.PackUnsupported(
            f"shape ({N} pods, {M} nodes) does not split into whole parts "
            f"per shard over {n_dev} devices")
    n_parts = pack_mod.pick_parts(N, M, n_shards=n_dev)

    node_s, node_s2, repl = _shardings(mesh)
    group_node_s = NamedSharding(mesh, P(None, NODE_AXIS))
    put = jax.device_put
    (req, group_id, rank, valid, g_term_req, g_term_forb, g_term_valid,
     g_anyof, g_anyof_valid, g_tol, g_ports, g_pref_req, g_pref_forb,
     g_pref_weight, labels, taints_hard, taints_soft, ports, node_ok,
     free_i, cap_i, host_mask, host_soft, loc, topo) = np_args
    args = (
        put(req, repl), put(group_id, repl), put(rank, repl),
        put(valid, repl),
        put(g_term_req, repl), put(g_term_forb, repl),
        put(g_term_valid, repl), put(g_anyof, repl),
        put(g_anyof_valid, repl), put(g_tol, repl), put(g_ports, repl),
        put(g_pref_req, repl), put(g_pref_forb, repl),
        put(g_pref_weight, repl),
        put(labels, node_s2), put(taints_hard, node_s2),
        put(taints_soft, node_s2), put(ports, node_s2),
        put(node_ok, node_s), put(free_i, node_s2), put(cap_i, node_s2),
        put(host_mask, group_node_s) if host_mask is not None else None,
        put(host_soft, group_node_s) if host_soft is not None else None,
        None,  # loc: gated above
        ((put(topo[0], node_s),) + tuple(put(a, repl) for a in topo[1:])
         if topo is not None else None),
    )
    from yunikorn_tpu.aot import runtime as aot_rt

    with mesh:
        assigned, free_after, feasible = aot_rt.aot_call(
            "mesh.pack_solve", pack_mod.pack_solve,
            (*args, jnp.int32(seed)),
            dict(n_parts=n_parts, partitioner="topo", n_shards=n_dev,
                 chunk=chunk, policy=policy,
                 score_cols=static_kwargs["score_cols"]),
            pending_ok=aot_pending, extra=("mesh", n_dev), lower_cm=mesh)
    return pack_mod.PackResult(assigned=assigned, free_after=free_after,
                               feasible=feasible, n_parts=n_parts,
                               seed=seed, partitioner="topo")


def cvx_solve_sharded(batch, node_arrays, mesh: Mesh, *,
                      policy: str = "binpacking", free_delta=None,
                      node_mask=None, ports_delta=None, seed: int = 0,
                      chunk: int = 512, device_state=None,
                      aot_pending: bool = False, learned=None,
                      aot_extra: tuple = ()):
    """Node-dimension sharded dispatch of ops.cvx_solve.cvx_solve.

    Same layout contract as solve_sharded — pod/group args replicate,
    node-side tensors shard along M. The full-fleet relaxation state X
    [N, M] and the per-pod feasibility/soft gathers partition along the
    node axis by GSPMD propagation (they derive from the [G, M]-sharded
    group tensors); the row-simplex projection's row reductions and the
    rounding's argmax-over-M become ICI all-reduces. learned: the
    two-tower params pytree for the warm-started dual, replicated (pass
    aot_extra=("policy", ckpt_hash) with it). Raises CvxUnsupported for
    batches outside the model."""
    from yunikorn_tpu.ops import cvx_solve as cvx_mod
    from yunikorn_tpu.ops.assign import SOLVE_ARG_NAMES

    if batch.locality is not None:
        raise cvx_mod.CvxUnsupported("locality batches take the greedy path")
    if batch.g_ports.view(np.uint32).any():
        raise cvx_mod.CvxUnsupported("host-port batches take the greedy path")
    n_dev = mesh.devices.size
    np_args, static_kwargs = assign_mod.prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        allow_req_device=False)
    N = np_args[SOLVE_ARG_NAMES.index("req")].shape[0]
    M = np_args[SOLVE_ARG_NAMES.index("free")].shape[0]
    if not cvx_mod.cvx_shape_supported(N, M):
        raise cvx_mod.CvxUnsupported(
            f"shape ({N} pods, {M} nodes) exceeds the full-fleet cell "
            "budget (the partitioned pack arm covers it)")

    node_s, node_s2, repl = _shardings(mesh)
    group_node_s = NamedSharding(mesh, P(None, NODE_AXIS))
    put = jax.device_put
    (req, group_id, rank, valid, g_term_req, g_term_forb, g_term_valid,
     g_anyof, g_anyof_valid, g_tol, g_ports, g_pref_req, g_pref_forb,
     g_pref_weight, labels, taints_hard, taints_soft, ports, node_ok,
     free_i, cap_i, host_mask, host_soft, loc, topo) = np_args
    args = (
        put(req, repl), put(group_id, repl), put(rank, repl),
        put(valid, repl),
        put(g_term_req, repl), put(g_term_forb, repl),
        put(g_term_valid, repl), put(g_anyof, repl),
        put(g_anyof_valid, repl), put(g_tol, repl), put(g_ports, repl),
        put(g_pref_req, repl), put(g_pref_forb, repl),
        put(g_pref_weight, repl),
        put(labels, node_s2), put(taints_hard, node_s2),
        put(taints_soft, node_s2), put(ports, node_s2),
        put(node_ok, node_s), put(free_i, node_s2), put(cap_i, node_s2),
        put(host_mask, group_node_s) if host_mask is not None else None,
        put(host_soft, group_node_s) if host_soft is not None else None,
        None,  # loc: gated above
        ((put(topo[0], node_s),) + tuple(put(a, repl) for a in topo[1:])
         if topo is not None else None),
    )
    learned_arg = (None if learned is None else jax.tree_util.tree_map(
        lambda a: put(jnp.asarray(a), repl), learned))
    from yunikorn_tpu.aot import runtime as aot_rt

    with mesh:
        assigned, free_after, feasible = aot_rt.aot_call(
            "mesh.cvx_solve", cvx_mod.cvx_solve,
            (*args, jnp.int32(seed), learned_arg),
            dict(chunk=chunk, policy=policy,
                 score_cols=static_kwargs["score_cols"]),
            pending_ok=aot_pending, extra=("mesh", n_dev) + tuple(aot_extra),
            lower_cm=mesh)
    return cvx_mod.CvxResult(assigned=assigned, free_after=free_after,
                             feasible=feasible, iters=cvx_mod.CVX_ITERS,
                             seed=seed, learned_dual=learned is not None)


def preempt_solve_sharded(np_args, mesh: Mesh, *, max_candidates: int,
                          aot_pending: bool = False):
    """Node-dimension sharded dispatch of ops.preempt_solve.preempt_solve.

    Same layout contract as solve_sharded: ask/group args replicate (tiny —
    at most 32 ask rows), node-side tensors — including the [M, V, R] victim
    tables — shard along M; the per-ask lexicographic argmin over nodes
    becomes a sharded reduce over ICI. np_args is
    ops.preempt_solve.prepare_preempt_args' tuple; victim tables already
    committed with this mesh's shardings (SnapshotEncoder.victim_arrays)
    are recognized by device_put and skip the transfer.
    """
    from yunikorn_tpu.ops import preempt_solve as ps_mod

    node_s, node_s2, repl = _shardings(mesh)
    node_s3 = NamedSharding(mesh, P(NODE_AXIS, None, None))
    (a_req, a_gid, a_prio, a_valid, g_term_req, g_term_forb, g_term_valid,
     g_anyof, g_anyof_valid, g_tol, labels, taints, node_ok, node_order,
     free_i, victim_req, victim_prio, victim_valid) = np_args
    put = jax.device_put
    args = (
        put(a_req, repl), put(a_gid, repl), put(a_prio, repl),
        put(a_valid, repl),
        put(g_term_req, repl), put(g_term_forb, repl), put(g_term_valid, repl),
        put(g_anyof, repl), put(g_anyof_valid, repl), put(g_tol, repl),
        put(labels, node_s2), put(taints, node_s2), put(node_ok, node_s),
        put(node_order, node_s),
        put(free_i, node_s2),
        put(victim_req, node_s3), put(victim_prio, node_s2),
        put(victim_valid, node_s2),
    )
    from yunikorn_tpu.aot import runtime as aot_rt

    with mesh:
        return aot_rt.aot_call(
            "mesh.preempt_solve", ps_mod.preempt_solve, args,
            {"max_candidates": max_candidates},
            pending_ok=aot_pending,
            extra=("mesh", mesh.devices.size), lower_cm=mesh)


def usage_fold_sharded(usage, mesh: Mesh):
    """psum-style cross-shard fold of the ledger usage mirror: the
    [S, T, K] per-shard confirmed-usage array, sharded over the shard
    axis like every node-dim tensor, reduces to the replicated [T, K]
    fleet totals with ONE ICI all-reduce — the admission precheck then
    reads pre-reduced fleet usage with zero lock acquisitions and zero
    host gathers. S must be divisible by the mesh size (shards and
    meshes are both powers of two by construction); parity with the
    single-device ops/gate_solve.usage_fold is pinned by test."""
    from jax.experimental.shard_map import shard_map

    n_dev = mesh.devices.size
    S = usage.shape[0]
    assert S % n_dev == 0, f"shard count {S} not divisible by mesh {n_dev}"

    fold = shard_map(
        lambda u: jax.lax.psum(jnp.sum(u, axis=0), NODE_AXIS),
        mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P())
    sharded = jax.device_put(usage, NamedSharding(mesh, P(NODE_AXIS)))
    return fold(sharded)
